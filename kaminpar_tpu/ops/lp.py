"""Bulk-synchronous size-constrained label propagation on device.

The TPU re-design of the reference's LP engine
(kaminpar-shm/label_propagation.h:83 LabelPropagation<...>).  The reference
runs an *asynchronous* LP: threads sweep shuffled chunks of nodes, rate each
node's adjacent clusters in a per-thread hash map
(find_best_cluster:461-541) and commit moves with CAS cluster-weight updates
(try_node_move:818, move_cluster_weight:2139).  Fine-grained CAS does not
map to TPUs, so this kernel makes the trade the reference's own Jet refiner
makes (refinement/jet/jet_refiner.cc:1-8): *bulk-synchronous rounds* of

  1. rate:    aggregate (node, neighbor-cluster) connection weights via the
              sorted segmented reduction in ops/segments.py;
  2. select:  per-node argmax over feasible clusters (weight cap), hashed
              random tie-breaking — the analog of find_best_cluster;
  3. commit:  capacity-respecting prefix acceptance per target cluster
              (ops/segments.accept_prefix_by_capacity), so the max cluster
              weight is *never* exceeded — stronger than the reference's
              relaxed CAS, which tolerates transient overshoot;
  4. apply:   scatter accepted labels, update cluster weights, refresh the
              active set (the analog of label_propagation.h:507-513).

Oscillation control (label_propagation.h avoids it by construction via
async updates; bulk-sync must handle it explicitly):
  * zero-gain ("tie") moves only follow a per-round hashed direction —
    of two clusters that rate equally, only the one with smaller hash may
    absorb the other, which turns 2-cycles into merges;
  * per-round random participation mask (cfg.participation < 1) — the
    bulk-sync analog of the reference's shuffled chunk scheduling
    (ChunkRandomLabelPropagation:1529), breaking symmetric flip patterns.

Whole multi-round loops run inside one jit via lax.while_loop with a
moved-count convergence test, so a full clustering is a single device
program launch.

Post-passes mirroring the reference:
  * cluster_isolated_nodes (label_propagation.h:872-917)
  * two-hop clustering of leftover singletons by favored cluster
    (label_propagation.h:919-1191)
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..graphs.csr import DeviceGraph
from ..telemetry import progress as progress_mod
from .rating import SCATTER_FALLBACK_FRAC
from .segments import (
    ACC_DTYPE,
    INT32_MIN,
    accept_prefix_by_capacity,
    aggregate_by_key,
    apply_move_weight_delta,
    argmax_per_segment,
    best_from_dense,
    best_from_rating_table,
    connection_to_label,
    connection_to_own_label,
    connection_to_own_rows,
    dense_block_ratings,
    expand_active_rows,
    hash_u32,
    hashed_rating_table,
    neighbor_any_true,
    packed_afterburner_gain,
    packed_afterburner_gain_rows,
    rating_top3_by_sort,
    rating_topk_rows,
)


@dataclass(frozen=True)
class LPConfig:
    """Knobs mirroring LabelPropagationConfig (label_propagation.h:36-74)
    plus the bulk-sync-specific ones."""

    num_iterations: int = 5  # lp_clusterer.cc default
    participation: float = 0.5  # per-round node participation probability
    allow_tie_moves: bool = True
    use_active_set: bool = True
    # post-pass toggles (two_hop_strategy / isolated_nodes_strategy enums)
    two_hop: bool = True
    cluster_isolated: bool = True
    # refinement mode: labels are blocks, moves need positive gain
    refinement: bool = False
    # distributed-only: restrict joins to clusters owned by the same device
    # (LocalLPClusterer analog, kaminpar-dist/.../local_lp_clusterer.cc —
    # no cross-PE clusters, so contraction needs no label migration)
    dist_local_only: bool = False
    # rating engine: "auto" delegates to ops/rating.select_engine (dense
    # for refinement-sized label spaces, the scatter-add slot engine
    # when the level's density fits the slot budget, sort2 rows
    # otherwise); "scatter"/"hash"/"sort"/"sort2"/"dense" force one
    rating: str = "auto"
    num_slots: int = 32  # hashed/scatter engine slots per node (per pass)
    # sort2: how many top clusters to read per node (n-sized reads, cheap)
    topk: int = 6
    # scatter engine: fall back to the exact sort rating when more than
    # this fraction of the round's active real nodes stay contested
    # (rationale at rating.SCATTER_FALLBACK_FRAC)
    scatter_fallback: float = SCATTER_FALLBACK_FRAC


def _select_engine(
    cfg: LPConfig,
    num_clusters: int,
    m_pad: int,
    has_communities: bool = False,
    n_pad: int | None = None,
) -> str:
    """Static (trace-time) rating engine choice — delegates to the
    density-adaptive rule in ops/rating.py (see its docstring for the
    selection order).  Inputs are shapes (host ints), so the choice is
    fixed per compiled executable.  The coarsener selects from MEASURED
    per-level density/skew instead and stamps the RESOLVED engine name
    into cfg.rating (never raw floats — cfg is a static jit argument,
    and per-level float stats would retrace every level)."""
    from .rating import select_engine

    engine, _ = select_engine(
        cfg.rating,
        num_clusters,
        n_pad if n_pad is not None else num_clusters,
        m_pad,
        num_slots=cfg.num_slots,
    )
    return engine


# Below this many edge slots a graph's full round is cheap enough that the
# delta machinery (extra programs, an n-wide scatter per round) is not
# worth compiling; shape-bucket floors put small levels at 2^20 anyway.
DELTA_MIN_EDGE_SLOTS = 1 << 22


def _delta_slots(graph: DeviceGraph, cfg: LPConfig, engine: str) -> int | None:
    """Static delta-round buffer size, or None when delta rounds are off.
    m_pad/4 covers active-edge fractions up to 25% at ~40% of a full
    round's cost (the crossover measured on v5e)."""
    if not cfg.use_active_set:
        return None
    if engine not in ("sort2", "dense", "scatter"):
        return None
    m_slots = graph.src.shape[0]
    # the scatter engine's per-round cost is segment-op bound, which
    # shrinks with buffer width immediately — its delta crossover sits
    # far lower than the sort engines' (measured in the round-9 CPU
    # profile; on v5e the sort2 crossover stays where it was).  min()
    # keeps the module-level knob authoritative when tests lower it.
    floor = (
        min(DELTA_MIN_EDGE_SLOTS, 1 << 20)
        if engine == "scatter" else DELTA_MIN_EDGE_SLOTS
    )
    if m_slots < floor:
        return None
    return m_slots // 4


def lp_round(
    graph: DeviceGraph,
    labels: jax.Array,
    cluster_weights: jax.Array,
    max_cluster_weight: jax.Array,
    active: jax.Array,
    salt: jax.Array,
    cfg: LPConfig,
    communities: jax.Array | None = None,
    rows=None,
    plans=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One bulk-synchronous LP round.

    Args:
      labels:            i32[n_pad] cluster id per node (clusters are node
                         ids during coarsening, block ids during refinement)
      cluster_weights:   i32[C] current weight per cluster
      max_cluster_weight:i32 scalar or i32[C] per-cluster cap
      active:            bool[n_pad] active set
      salt:              i32 per-round randomness salt
      rows:              optional expand_active_rows(...) result — a delta
                         round: only the active nodes' rows are rated, and
                         every edge-wide pass shrinks to the row buffer
                         (sort2/dense engines only)

    Returns (new_labels, new_cluster_weights, new_active, num_moved).
    """
    n_pad = graph.n_pad
    m_slots = graph.src.shape[0]
    C = cluster_weights.shape[0]
    cap = jnp.broadcast_to(max_cluster_weight, (C,))
    engine = _select_engine(
        cfg, C, graph.m_pad, communities is not None, n_pad=n_pad
    )
    if rows is not None and engine not in ("sort2", "dense", "scatter"):
        raise ValueError(f"delta rounds are not supported by engine {engine}")

    # nodes the rating engine could not rate exhaustively this round
    # (scatter engine only): they are barred from moving and stay active
    # so the next round's re-salted slots give them another chance
    barred = jnp.zeros(n_pad, dtype=bool)

    # -- shared row view: either the raw CSR edge arrays (full round; src
    # is CSR-sorted so rows are contiguous spans) or the compacted active-
    # row buffer (delta round)
    if engine in ("sort2", "dense", "scatter"):
        if rows is not None:
            owner_c, owner_key, edge_id, valid, start, end = rows
            eid = jnp.clip(edge_id, 0, m_slots - 1)
            dst_b = jnp.where(valid, graph.dst[eid], n_pad - 1)
            w_b = jnp.where(valid, graph.edge_w[eid], 0)
            deg_eff = end - start
        else:
            owner_c = graph.src
            owner_key = graph.src
            dst_b = graph.dst
            w_b = graph.edge_w
            start = graph.row_ptr[:-1]
            end = graph.row_ptr[1:]
            deg_eff = graph.degrees

    # -- rate: per-node best non-own cluster under the weight cap, plus
    # the exact connection to the own cluster.
    if engine == "sort2":
        # top-K rated clusters per row (two buffer-wide sorts, no
        # scatters), then node-level own-exclusion + feasibility +
        # community fallback chain.  The own-cluster connection is EXACT:
        # a streaming masked cumsum over the row spans (one extra gather
        # for the owner's label), replacing the old top-K upper-bound
        # estimate that silently under-moved on huge graphs.
        # On dense coarse levels (hundreds of adjacent clusters, most
        # near the weight cap) a deeper candidate list keeps merges
        # flowing — the reads are n-wide gathers, essentially free.
        avg_degree = graph.m_pad / max(C, 1)
        K = cfg.topk if avg_degree <= 32 else max(cfg.topk, 16)
        if plans is not None and rows is None:
            from .lane_gather import INTERPRET, lane_gather

            # lane-routed full round: labels[dst] via the Pallas
            # dynamic_gather kernel (streaming speed) in the plan's slot
            # order; the rating sort re-groups by owner anyway, and the
            # own-connection rides sort1 as a 4th operand, so nothing
            # ever returns to CSR order (ops/lane_gather.py rationale)
            nb_r = lane_gather(labels, plans.plan, interpret=INTERPRET)
            own_rt = labels[plans.src_idx]
            w_own_r = jnp.where(nb_r == own_rt, plans.edge_w, 0)
            topk, w_cur = rating_topk_rows(
                plans.owner_key, nb_r, plans.edge_w,
                graph.row_ptr[1:], graph.degrees, salt, K,
                w_own=w_own_r,
            )
            labs = topk[0::2]
            vals = topk[1::2]
            own = labels
        else:
            nb = jnp.where(valid, labels[dst_b], -1) if rows is not None else (
                labels[dst_b]
            )
            own_slot = labels[owner_c]
            topk = rating_topk_rows(owner_key, nb, w_b, end, deg_eff, salt, K)
            labs = topk[0::2]
            vals = topk[1::2]
            own = labels
            w_cur = connection_to_own_rows(nb, w_b, own_slot, start, end)

        def fits(lab):
            lab_c = jnp.clip(lab, 0, C - 1)
            ok = (lab >= 0) & (
                cluster_weights[lab_c].astype(ACC_DTYPE)
                + graph.node_w.astype(ACC_DTYPE)
                <= cap[lab_c]
            )
            if communities is not None:
                # v-cycle community restriction: a cluster label is a node
                # id, so the cluster's community is the label node's
                lab_n = jnp.clip(lab, 0, n_pad - 1)
                ok = ok & (communities[lab_n] == communities)
            return ok

        best = jnp.full(n_pad, -1, dtype=jnp.int32)
        best_w = jnp.full(n_pad, INT32_MIN, dtype=ACC_DTYPE)
        for lab_j, val_j in zip(reversed(labs), reversed(vals)):
            ok = (lab_j != own) & fits(lab_j)
            best = jnp.where(ok, lab_j, best)
            best_w = jnp.where(ok, val_j, best_w)
    elif engine == "scatter":
        # the one-launch scatter-add engine (ops/rating.py): ONE edge
        # gather (labels[dst]), then segment-sum slot tables — no edge
        # sort anywhere.  Rows the two elimination passes could not
        # rate exhaustively are barred from moving; when too many rows
        # are barred the whole round's rating falls back to the exact
        # sort engine via lax.cond (collision-safe fallback — only the
        # taken branch executes).
        from .rating import best_from_slots, scatter_slot_ratings

        nb = (
            jnp.where(valid, labels[dst_b], -1)
            if rows is not None
            else labels[dst_b]
        )
        valid_slots = valid if rows is not None else None
        seg_owner = (
            jnp.where(valid, owner_c, -1) if rows is not None else owner_c
        )
        node_ids0 = jnp.arange(n_pad, dtype=jnp.int32)
        is_real0 = node_ids0 < graph.n

        # the slot tables are built ONCE, outside the cond: the fallback
        # predicate needs fully_rated either way, and the (n, 2S) table
        # is the cheap part to carry into the taken branch
        slot_label, slot_w, fully_rated = scatter_slot_ratings(
            owner_c, nb, w_b, n_pad, cfg.num_slots, salt,
            valid=valid_slots, spans=(start, end),
        )

        def scatter_rate(_):
            b, bw, w_own = best_from_slots(
                slot_label, slot_w, labels, cluster_weights,
                graph.node_w, cap, salt, communities=communities,
            )
            return b, bw, w_own, ~fully_rated

        def sort_rate(_):
            seg_g, key_g, w_g = aggregate_by_key(seg_owner, nb, w_b)
            key_c = jnp.clip(key_g, 0, C - 1)
            seg_c = jnp.clip(seg_g, 0, n_pad - 1)
            fits_g = (
                cluster_weights[key_c].astype(ACC_DTYPE)
                + graph.node_w[seg_c].astype(ACC_DTYPE)
                <= cap[key_c]
            )
            feasible = (seg_g >= 0) & (key_g != labels[seg_c]) & fits_g
            if communities is not None:
                key_n = jnp.clip(key_g, 0, n_pad - 1)
                feasible = feasible & (
                    communities[key_n] == communities[seg_c]
                )
            b, bw = argmax_per_segment(
                seg_g, key_g, w_g, n_pad, tie_salt=salt, feasible=feasible
            )
            w_own = connection_to_label(seg_g, key_g, w_g, labels, n_pad)
            return b, bw, w_own, jnp.zeros(n_pad, dtype=bool)

        # fallback predicate on values already in hand: barred fraction
        # of the ACTIVE real nodes (an n-wide reduce, no extra edge op)
        act_real = active & is_real0
        # node counts <= n, ID domain  # tpulint: disable=R3
        n_bar = jnp.sum(act_real & ~fully_rated, dtype=jnp.int32)
        # node counts <= n, ID domain  # tpulint: disable=R3
        n_act = jnp.sum(act_real, dtype=jnp.int32)
        use_scatter = n_bar.astype(jnp.float32) <= (
            jnp.float32(cfg.scatter_fallback) * n_act.astype(jnp.float32)
        )
        best, best_w, w_cur, barred = lax.cond(
            use_scatter, scatter_rate, sort_rate, None
        )
        best = jnp.where(barred, -1, best)
        best_w = jnp.where(barred, INT32_MIN, best_w)
    elif engine == "dense":
        if plans is not None and rows is None:
            from .lane_gather import routed_block_ratings

            conn = routed_block_ratings(plans, labels, C, n_pad)
        else:
            conn = dense_block_ratings(owner_c, dst_b, w_b, labels, n_pad, C)
        best, best_w, w_cur = best_from_dense(
            conn, labels, cluster_weights, graph.node_w, cap, salt,
            communities=communities,
        )
    elif engine == "hash":
        neighbor_cluster = labels[graph.dst]
        slot_label, slot_w = hashed_rating_table(
            graph.src, neighbor_cluster, graph.edge_w, n_pad,
            cfg.num_slots, salt,
        )
        best, best_w = best_from_rating_table(
            slot_label, slot_w, labels, cluster_weights, graph.node_w,
            cap, salt ^ 0x51AB, communities=communities,
        )
        w_cur = connection_to_own_label(
            graph.src, neighbor_cluster, graph.edge_w, labels, n_pad
        )
    else:  # sort (exact enumeration of every adjacent cluster)
        neighbor_cluster = labels[graph.dst]
        seg_g, key_g, w_g = aggregate_by_key(
            graph.src, neighbor_cluster, graph.edge_w
        )
        key_c = jnp.clip(key_g, 0, C - 1)
        seg_c = jnp.clip(seg_g, 0, n_pad - 1)
        fits = (
            cluster_weights[key_c].astype(ACC_DTYPE)
            + graph.node_w[seg_c].astype(ACC_DTYPE)
            <= cap[key_c]
        )
        feasible = (seg_g >= 0) & (key_g != labels[seg_c]) & fits
        if communities is not None:
            # v-cycle community restriction: a cluster label is a node id,
            # so the cluster's community is the label node's community
            feasible = feasible & (communities[key_c] == communities[seg_c])
        best, best_w = argmax_per_segment(
            seg_g, key_g, w_g, n_pad, tie_salt=salt, feasible=feasible
        )
        w_cur = connection_to_label(seg_g, key_g, w_g, labels, n_pad)

    # -- select ----------------------------------------------------------
    gain = best_w - w_cur
    tie_dir_ok = hash_u32(best, salt ^ 0x5BD1) < hash_u32(labels, salt ^ 0x5BD1)
    if cfg.refinement:
        improves = gain > 0
    else:
        improves = (gain > 0) | (
            cfg.allow_tie_moves & (gain == 0) & (best_w > 0) & tie_dir_ok
        )
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    participate = hash_u32(node_ids, salt ^ 0x27D4) < jnp.int32(
        cfg.participation * 2147483647.0
    )
    wants = (
        (best >= 0) & (best != labels) & improves & active & (node_ids < graph.n)
    )
    target = jnp.where(wants & participate, best, -1)

    if cfg.refinement:
        # afterburner (Jet's filter step, jet_refiner.cc:133-170): in a
        # bulk-synchronous round, simultaneous moves of ADJACENT nodes can
        # increase the cut even though each individual gain is positive;
        # keep only candidates whose adjusted gain stays positive.  The
        # async reference never needs this (moves see latest labels);
        # without it bulk-sync LP refinement can DOUBLE the cut.
        # `wants` is deliberately NOT masked: filtered/unsampled nodes
        # must stay in the convergence count and the active set.
        # Row-packed (n, 3) tables keep this at TWO edge-wide gathers
        # with EXACT gains (the naive six per-endpoint scalar gathers
        # were ~10x a Jet iteration at equal shape; gathers are charged
        # per index, so the 3-wide rows ride along free).
        candidate = target >= 0
        next_lab = jnp.where(candidate, target, labels)
        if rows is not None:
            # candidates are active, so every candidate's full row is in
            # the buffer — the filter shrinks to buffer width
            adj_gain, _, _ = packed_afterburner_gain_rows(
                owner_c, dst_b, w_b, start, end,
                labels, next_lab, gain, candidate, C,
            )
        else:
            adj_gain = packed_afterburner_gain(
                graph.src, graph.dst, graph.edge_w, graph.row_ptr,
                labels, next_lab, gain, candidate, C,
            )
        target = jnp.where(candidate & (adj_gain > 0), target, -1)

    # -- commit: never exceed the cap even under simultaneous joins ------
    headroom = jnp.maximum(cap - cluster_weights.astype(ACC_DTYPE), 0)
    prio = hash_u32(node_ids, salt ^ 0x165667B1)
    accept = accept_prefix_by_capacity(target, prio, graph.node_w, headroom)

    # -- apply -----------------------------------------------------------
    new_labels = jnp.where(accept, target, labels)
    new_cluster_weights = apply_move_weight_delta(
        cluster_weights, labels, target, accept, graph.node_w
    )

    # -- active set refresh (label_propagation.h:507-513): a node is active
    # next round iff it or one of its neighbors moved this round, or it
    # wanted a move but was unsampled/capacity-rejected.  This both
    # mirrors the reference's semantics AND feeds the delta rounds: the
    # next round's row buffer holds exactly these nodes' rows.
    if cfg.use_active_set:
        if rows is not None:
            # movers' rows are in the buffer; flag their endpoints with
            # one buffer-wide scatter
            moved_slot = accept[owner_c] & valid
            neigh_moved = (
                jnp.zeros(n_pad, dtype=jnp.int32)
                .at[dst_b]
                .max(moved_slot.astype(jnp.int32), mode="drop")
                > 0
            )
        else:
            # one edge gather + streaming row sums (scatter-free; see
            # segments.neighbor_any_true)
            neigh_moved = neighbor_any_true(accept, graph.dst, graph.row_ptr)
        # retention: a node stays active while it still has a USABLE
        # candidate — improving, or a positive-weight tie (clustering).
        # Gating retention on `wants` deactivated tie-blocked nodes
        # forever even though the hashed tie direction re-rolls every
        # round (the salt changes), which froze coarsening into ~20
        # limping levels on dense coarse graphs; unsampled
        # (participation) and capacity-rejected nodes are likewise kept.
        # `& active` keeps full and delta rounds bitwise-identical: a
        # deactivated node is reactivated ONLY by a neighbor's move in
        # both (a delta round never rates inactive rows, so a full round
        # must not resurrect them from its all-rows rating either)
        may_move_later = active & (best >= 0) & (best != labels) & (
            (gain > 0)
            | (
                (not cfg.refinement)
                & cfg.allow_tie_moves
                & (gain == 0)
                & (best_w > 0)
            )
        )
        # barred rows (scatter engine: still-contested after both
        # elimination passes) keep their active bit — the next round's
        # salt re-rolls their slots, so they get rated again
        new_active = (
            accept | neigh_moved | (may_move_later & ~accept)
            | (barred & active)
        )
    else:
        new_active = jnp.ones_like(active)

    # convergence is judged on *wanting* nodes, not sampled movers: a round
    # where the participation sample happens to move nobody must not stop
    # the loop while unsampled nodes still have improving moves
    # wanting-node count <= n, ID domain  # tpulint: disable=R3
    num_wanting = jnp.sum(wants, dtype=jnp.int32)
    return new_labels, new_cluster_weights, new_active, num_wanting


def _round_with_delta(
    graph: DeviceGraph,
    labels: jax.Array,
    weights: jax.Array,
    max_cluster_weight: jax.Array,
    active: jax.Array,
    salt: jax.Array,
    cfg: LPConfig,
    communities: jax.Array | None,
    i: jax.Array,

    plans=None,
):
    """One LP round, delta-dispatched: after the first round, when the
    active nodes' rows fit the m_pad/4 buffer, run the round on the
    compacted buffer instead of the full edge list (lax.cond — only the
    taken branch executes).  The active set collapses to movers + their
    neighbors after round 1, so later rounds cost O(active rows), not m —
    the bulk-synchronous answer to the async reference's active-set
    work-skipping (label_propagation.h:507-513)."""
    C = weights.shape[0]
    engine = _select_engine(
        cfg, C, graph.m_pad, communities is not None, n_pad=graph.n_pad
    )
    dslots = _delta_slots(graph, cfg, engine)
    if dslots is None:
        return lp_round(
            graph, labels, weights, max_cluster_weight, active, salt, cfg,
            communities=communities, plans=plans,
        )
    deg = graph.degrees

    def delta_fn(op):
        labels, weights, active = op
        rows = expand_active_rows(graph.row_ptr, deg, active, dslots)
        return lp_round(
            graph, labels, weights, max_cluster_weight, active, salt, cfg,
            communities=communities, rows=rows,
        )

    def full_fn(op):
        labels, weights, active = op
        return lp_round(
            graph, labels, weights, max_cluster_weight, active, salt, cfg,
            communities=communities, plans=plans,
        )

    # active-degree total <= m_pad < 2^31 (device layout)
    # tpulint: disable=R3
    total = jnp.sum(jnp.where(active & (deg > 0), deg, 0), dtype=jnp.int32)
    pred = (i > 0) & (total <= dslots)
    return lax.cond(pred, delta_fn, full_fn, (labels, weights, active))


@partial(jax.jit, static_argnames=("cfg", "num_iterations", "has_communities"))
def _lp_cluster_impl(
    graph: DeviceGraph,
    max_cluster_weight: jax.Array,
    seed: jax.Array,
    communities: jax.Array,
    cfg: LPConfig,
    num_iterations: int | None,
    has_communities: bool,
    plans=None,
    stats=None,
):
    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    comm = communities if has_communities else None
    labels, weights, stats = _lp_cluster_fused_rounds(
        graph, max_cluster_weight, seed, comm, cfg, iters, plans, stats
    )
    labels = _lp_cluster_postpasses_traced(
        graph, labels, weights, max_cluster_weight, seed, cfg,
        has_communities,
    )
    return labels if stats is None else (labels, stats)


def _lp_cluster_postpasses_traced(
    graph, labels, weights, max_cluster_weight, seed, cfg: LPConfig,
    has_communities: bool,
):
    if not has_communities:
        # community-restricted clustering (v-cycles) skips the singleton
        # post-passes: they could merge across community boundaries
        if cfg.cluster_isolated:
            labels, weights = cluster_isolated_nodes(
                graph, labels, weights, max_cluster_weight
            )
        if cfg.two_hop:
            labels, weights = two_hop_cluster(
                graph, labels, weights, max_cluster_weight, seed, cfg
            )
    return labels


_lp_cluster_postpasses = jax.jit(
    _lp_cluster_postpasses_traced,
    static_argnames=("cfg", "has_communities"),
)


def _lp_cluster_chunked(
    graph: DeviceGraph,
    max_cluster_weight: jax.Array,
    seed: jax.Array,
    comm,
    cfg: LPConfig,
    iters: int,
    has_communities: bool,
    plans=None,
) -> jax.Array:
    """One clustering round per launch — the TPU-worker watchdog guard
    above the fused budget (a multi-round fused clustering loop at
    128M-slot shapes is a multi-minute single launch that reproducibly
    kills the worker; the Jet/LP-refine chunking already guards the
    same failure mode).  Lives OUTSIDE jit: the convergence exit reads
    `moved` back per round.  Visits identical states to the fused loop:
    the python salt masked to 31 bits equals the traced int32-wraparound
    product (bit 31 of an addend cannot reach lower sum bits), and all
    state is integer, so results are bitwise-equal (tested)."""
    from ..caching import record_transfer
    from ..telemetry import ledger

    n_pad = graph.n_pad
    labels = jnp.arange(n_pad, dtype=jnp.int32)
    weights = graph.node_w.astype(ACC_DTYPE)
    if weights is graph.node_w:
        # astype was a no-op alias (node weights already ACC_DTYPE);
        # round 0 donates the carry, so an aliased buffer would delete
        # the graph's own node weights — force a fresh copy
        weights = jnp.array(weights, copy=True)
    active = jnp.ones(n_pad, dtype=bool)
    # progress capture, host-side: the chunked driver already reads the
    # convergence scalar back every round, so the series costs one more
    # scalar readback per round (telemetry-enabled runs only)
    rec = progress_mod.capture()
    t0 = progress_mod.now()
    moved_series, active_series = [], []
    for i in range(iters):
        off = jnp.int32((i * 1566083941) & 0x7FFFFFFF)
        salt = (jnp.asarray(seed, jnp.int32) * 131071 + off) & 0x7FFFFFFF
        tok = ledger.donation_begin((labels, weights, active),
                                    kind="lp-round")
        labels, weights, active, moved = _lp_cluster_round_launch(
            graph, labels, weights, max_cluster_weight, active,
            salt, jnp.int32(i), cfg, comm, plans,
        )
        ledger.donation_end(tok)
        record_transfer("d2h", getattr(moved, "nbytes", 8),
                        kind="stat-pull")
        if rec:
            moved_series.append(int(moved))
            active_series.append(int(jnp.sum(active)))
        if int(moved) == 0:
            break
    if rec:
        progress_mod.emit_host(
            "lp", {"moved": moved_series, "active": active_series},
            t0=t0, phase="cluster", launch="chunked",
        )
    return _lp_cluster_postpasses(
        graph, labels, weights, max_cluster_weight, seed, cfg,
        has_communities,
    )


# the round carry (labels, weights, active) is donated: each chunked
# round's outputs alias the previous round's buffers instead of
# doubling the carry footprint per launch.  The execution ledger's
# donation audit verifies the aliasing was honored (telemetry/ledger.py)
@partial(jax.jit, static_argnames=("cfg", "has_comm"),
         donate_argnums=(1, 2, 4))
def _lp_cluster_round_launch_jit(
    graph, labels, weights, max_cluster_weight, active, salt, i,
    cfg: LPConfig, communities, has_comm: bool, plans=None,
):
    return _round_with_delta(
        graph, labels, weights, max_cluster_weight, active, salt, cfg,
        communities if has_comm else None, i, plans=plans,
    )


def _lp_cluster_round_launch(
    graph, labels, weights, max_cluster_weight, active, salt, i,
    cfg: LPConfig, comm, plans=None,
):
    has_comm = comm is not None
    # the dummy is a 1-element array (never read when has_comm is False)
    return _lp_cluster_round_launch_jit(
        graph, labels, weights, max_cluster_weight, active, salt, i, cfg,
        comm if has_comm else jnp.zeros(1, dtype=jnp.int32),
        has_comm, plans,
    )


def _lp_cluster_fused_rounds(
    graph, max_cluster_weight, seed, comm, cfg: LPConfig, iters: int,
    plans=None, stats=None,
):
    """The fused multi-round clustering loop (one launch).

    `stats` is an optional progress buffer (telemetry/progress.py)
    threaded through the carry; None (the default) leaves the jaxpr
    bitwise-identical to the uninstrumented loop — the zero-overhead-
    when-disabled contract tests/test_telemetry.py pins."""
    n_pad = graph.n_pad
    labels0 = jnp.arange(n_pad, dtype=jnp.int32)
    weights0 = graph.node_w.astype(ACC_DTYPE)
    active0 = jnp.ones(n_pad, dtype=bool)

    def cond(state):
        i, _, _, _, moved, _ = state
        return (i < iters) & (moved != 0)

    def body(state):
        i, labels, weights, active, _, stats = state
        salt = (seed.astype(jnp.int32) * 131071 + i * 1566083941) & 0x7FFFFFFF
        labels, weights, active, moved = _round_with_delta(
            graph, labels, weights, max_cluster_weight, active, salt,
            cfg, comm, i, plans=plans,
        )
        if stats is not None:  # trace-time guard (None adds no carry)
            stats = progress_mod.record(
                stats, i, moved, jnp.sum(active)
            )
        return (i + 1, labels, weights, active, moved, stats)

    init = (jnp.int32(0), labels0, weights0, active0, jnp.int32(1), stats)
    _, labels, weights, _, _, stats = lax.while_loop(cond, body, init)
    return labels, weights, stats


def lp_cluster(
    graph: DeviceGraph,
    max_cluster_weight: jax.Array,
    seed: jax.Array,
    cfg: LPConfig = LPConfig(),
    num_iterations: int | None = None,
    communities: jax.Array | None = None,
) -> jax.Array:
    """Size-constrained LP clustering (analog of LPClustering::compute_
    clustering, lp_clusterer.cc:90-110): every node starts as a singleton,
    runs `num_iterations` rounds or until no node moves, then clusters
    isolated nodes and two-hop-merges leftover singletons.

    `communities` (optional i32[n_pad]) restricts clustering to within
    communities — nodes only join clusters whose label node shares their
    community (Clusterer::set_communities analog, used by v-cycles).

    Returns i32[n_pad] cluster labels (values are node ids; pad slots keep
    their own id)."""
    from .lane_gather import maybe_edge_plans
    from .segments import MAX_FUSED_EDGE_SLOTS

    has_comm = communities is not None
    iters = (
        num_iterations if num_iterations is not None else cfg.num_iterations
    )
    # plan building does host readbacks, so it happens HERE (eagerly,
    # outside jit) and the plan rides into the traced rounds as an
    # ordinary pytree argument — NEVER as a captured constant, which the
    # shape-bucketed jit cache would wrongly share across levels
    plans = maybe_edge_plans(graph)
    if graph.src.shape[0] > MAX_FUSED_EDGE_SLOTS and iters > 1:
        # watchdog guard: the dispatch must stay OUTSIDE jit — the
        # chunked loop reads the convergence flag back per round
        return _lp_cluster_chunked(
            graph, max_cluster_weight, seed, communities, cfg, iters,
            has_comm, plans,
        )
    if communities is None:
        communities = jnp.zeros(graph.n_pad, dtype=jnp.int32)
    return progress_mod.instrumented(
        lambda stats: _lp_cluster_impl(
            graph,
            max_cluster_weight,
            seed,
            communities,
            cfg,
            num_iterations,
            has_comm,
            plans,
            stats,
        ),
        "lp", ("moved", "active"), rows=iters, phase="cluster",
    )


# round carry (part, bw, active) donated — see _lp_cluster_round_launch_jit
@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1, 2, 4))
def _lp_refine_round_launch(graph, part, bw, max_block_weights, active,
                            salt, i, cfg: LPConfig, plans=None):
    return _round_with_delta(
        graph, part, bw, max_block_weights, active, salt, cfg, None, i,
        plans=plans,
    )


def lp_refine(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    cfg: LPConfig = LPConfig(refinement=True),
    num_iterations: int | None = None,
) -> jax.Array:
    """LP refinement entry point.  Above MAX_FUSED_EDGE_SLOTS a
    multi-round fused launch runs for minutes and reproducibly kills the
    TPU worker (same failure mode Jet's chunking guards against), so
    huge graphs run one round per launch — keeping the fused path's
    active set and moved==0 convergence exit across launches."""
    from .segments import MAX_FUSED_EDGE_SLOTS

    from .lane_gather import maybe_edge_plans

    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    if not cfg.refinement:
        # normalize once for BOTH launch strategies so the chunked path
        # never runs with clustering semantics (tie moves, no positive-gain
        # restriction); replace() preserves the caller's engine settings
        cfg = replace(cfg, allow_tie_moves=False, refinement=True)
    plans = maybe_edge_plans(graph)  # eager: host readbacks (see lp_cluster)
    if graph.src.shape[0] > MAX_FUSED_EDGE_SLOTS and iters > 1:
        from ..caching import record_transfer
        from ..telemetry import ledger

        rec = progress_mod.capture()
        t0 = progress_mod.now()
        part = jnp.clip(partition, 0, k - 1).astype(jnp.int32)
        bw = jax.ops.segment_sum(
            graph.node_w.astype(ACC_DTYPE), part, num_segments=k
        )
        active = jnp.ones(graph.n_pad, dtype=bool)
        moved_series, active_series = [], []
        for i in range(iters):
            # equivalent to the fused while_loop's traced int32-wraparound
            # `i * 1566083941`: the final & 0x7FFFFFFF drops bit 31, and
            # bit 31 of an addend cannot reach lower sum bits — so masking
            # the python product to 31 bits visits identical states
            off = jnp.int32((i * 1566083941) & 0x7FFFFFFF)
            salt = (jnp.asarray(seed, jnp.int32) * 92821 + off) & 0x7FFFFFFF
            tok = ledger.donation_begin((part, bw, active),
                                        kind="lp-round")
            part, bw, active, moved = _lp_refine_round_launch(
                graph, part, bw, max_block_weights, active, salt,
                jnp.int32(i), cfg, plans
            )
            ledger.donation_end(tok)
            record_transfer("d2h", getattr(moved, "nbytes", 8),
                            kind="stat-pull")
            if rec:
                moved_series.append(int(moved))
                active_series.append(int(jnp.sum(active)))
            if int(moved) == 0:
                break
        if rec:
            progress_mod.emit_host(
                "lp", {"moved": moved_series, "active": active_series},
                t0=t0, phase="refine", launch="chunked",
            )
        return part
    return progress_mod.instrumented(
        lambda stats: _lp_refine_fused(
            graph, partition, k, max_block_weights, seed, cfg, iters,
            plans, stats,
        ),
        "lp", ("moved", "active"), rows=iters, phase="refine",
    )


@partial(jax.jit, static_argnames=("cfg", "k", "num_iterations"))
def _lp_refine_fused(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    cfg: LPConfig = LPConfig(refinement=True),
    num_iterations: int | None = None,
    plans=None,
    stats=None,
):
    """LP refinement (analog of LabelPropagationRefiner,
    kaminpar-shm/refinement/lp/lp_refiner.cc): the LP kernel with clusters
    fixed to the k blocks, moves restricted to strictly positive gain under
    the per-block max weights.  Returns the refined partition (plus the
    progress buffer when one was threaded in — see
    _lp_cluster_fused_rounds on the stats/None contract)."""
    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    if not cfg.refinement:
        cfg = replace(cfg, allow_tie_moves=False, refinement=True)
    n_pad = graph.n_pad
    part0 = jnp.clip(partition, 0, k - 1).astype(jnp.int32)
    bw0 = jax.ops.segment_sum(
        graph.node_w.astype(ACC_DTYPE), part0, num_segments=k
    )
    active0 = jnp.ones(n_pad, dtype=bool)
    def cond(state):
        i, _, _, _, moved, _ = state
        return (i < iters) & (moved != 0)

    def body(state):
        i, part, bw, active, _, stats = state
        salt = (seed.astype(jnp.int32) * 92821 + i * 1566083941) & 0x7FFFFFFF
        part, bw, active, moved = _round_with_delta(
            graph, part, bw, max_block_weights, active, salt, cfg, None, i,
            plans=plans,
        )
        if stats is not None:  # trace-time guard (None adds no carry)
            stats = progress_mod.record(
                stats, i, moved, jnp.sum(active)
            )
        return (i + 1, part, bw, active, moved, stats)

    init = (jnp.int32(0), part0, bw0, active0, jnp.int32(1), stats)
    _, part, _, _, _, stats = lax.while_loop(cond, body, init)
    return part if stats is None else (part, stats)


def cluster_isolated_nodes(
    graph: DeviceGraph,
    labels: jax.Array,
    cluster_weights: jax.Array,
    max_cluster_weight: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Merge isolated singleton nodes into shared clusters under the weight
    cap (label_propagation.h:872-917).

    Isolated nodes are ordered by id; node i's tentative bin is
    floor(prefix_weight / cap); within each bin the capacity-respecting
    prefix pass rejects overflow (exactness), rejected nodes stay singleton.
    The first member of each bin is its leader; members adopt the leader's
    label."""
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    is_real = node_ids < graph.n
    deg = graph.degrees
    iso = (deg == 0) & is_real & (labels == node_ids)

    cap = jnp.maximum(jnp.broadcast_to(max_cluster_weight, ()).astype(ACC_DTYPE), 1)
    w = jnp.where(iso, graph.node_w, 0).astype(ACC_DTYPE)
    cum_before = jnp.cumsum(w) - w
    bin_id = jnp.where(iso, (cum_before // cap).astype(jnp.int32), -1)

    # leader of each bin = first isolated node in it
    first_in_bin = jax.ops.segment_min(
        jnp.where(iso, node_ids, jnp.iinfo(jnp.int32).max),
        jnp.clip(bin_id, 0, n_pad - 1),
        num_segments=n_pad,
    )
    leader = jnp.where(iso, first_in_bin[jnp.clip(bin_id, 0, n_pad - 1)], -1)
    # joiners (non-leaders) move into the leader's cluster, capacity-checked
    joiner = iso & (leader != node_ids) & (leader >= 0)
    target = jnp.where(joiner, leader, -1)
    headroom = jnp.maximum(
        jnp.broadcast_to(max_cluster_weight, (n_pad,)).astype(ACC_DTYPE)
        - cluster_weights.astype(ACC_DTYPE),
        0,
    )
    accept = accept_prefix_by_capacity(
        target, node_ids, graph.node_w, headroom
    )
    new_labels = jnp.where(accept, target, labels)
    return new_labels, apply_move_weight_delta(
        cluster_weights, labels, target, accept, graph.node_w
    )


def two_hop_cluster(
    graph: DeviceGraph,
    labels: jax.Array,
    cluster_weights: jax.Array,
    max_cluster_weight: jax.Array,
    seed: jax.Array,
    cfg: LPConfig = LPConfig(),
) -> Tuple[jax.Array, jax.Array]:
    """Two-hop clustering of leftover singletons (label_propagation.h:919-
    1191): singleton nodes that share the same *favored cluster* (their
    best-rated cluster, ignoring the weight cap) are merged with each other
    — they are two hops apart through that cluster.  The smallest singleton
    id per favored cluster becomes the leader; the rest join it under the
    weight cap."""
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    is_real = node_ids < graph.n
    singleton = (
        (labels == node_ids)
        & (cluster_weights[jnp.clip(labels, 0, n_pad - 1)] == graph.node_w)
        & is_real
        & (graph.degrees > 0)
    )

    # favored cluster = unconstrained best-rated cluster (same engine
    # dispatch as lp_round; a singleton's own label never appears among
    # its neighbors' labels, so own-exclusion is harmless here)
    neighbor_cluster = labels[graph.dst]
    engine = _select_engine(
        cfg, cluster_weights.shape[0], graph.m_pad, n_pad=n_pad
    )
    if engine == "scatter":
        # favored cluster = unconstrained best rated cluster from the
        # scatter slot tables, with the same collision-safe fallback as
        # the round rating: when too many singleton rows stay contested
        # the exact sort rating takes over (lax.cond, one branch runs)
        from .rating import best_from_slots, scatter_slot_ratings

        slot_label, slot_w, fully_rated = scatter_slot_ratings(
            graph.src, neighbor_cluster, graph.edge_w, n_pad,
            cfg.num_slots, seed,
        )

        def scatter_fav(_):
            fav, fav_w, _ = best_from_slots(
                slot_label, slot_w, labels, cluster_weights,
                graph.node_w,
                jnp.broadcast_to(
                    max_cluster_weight, (cluster_weights.shape[0],)
                ),
                seed, require_fit=False,
            )
            # zero-weight ratings (sparsified-away edges) are not real
            # favorites — same mask as the sort2/hash branches
            return jnp.where(fully_rated & (fav_w > 0), fav, -1)

        def sort_fav(_):
            seg_g, key_g, w_g = aggregate_by_key(
                graph.src, neighbor_cluster, graph.edge_w
            )
            fav, _ = argmax_per_segment(
                seg_g, key_g, w_g, n_pad, tie_salt=seed
            )
            return fav

        # singleton counts <= n, ID domain  # tpulint: disable=R3
        n_bad = jnp.sum(singleton & ~fully_rated, dtype=jnp.int32)
        # singleton counts <= n, ID domain  # tpulint: disable=R3
        n_sing = jnp.sum(singleton, dtype=jnp.int32)
        favored = lax.cond(
            n_bad.astype(jnp.float32)
            <= jnp.float32(cfg.scatter_fallback)
            * n_sing.astype(jnp.float32),
            scatter_fav, sort_fav, None,
        )
    elif engine == "sort2":
        # a singleton's own label never appears among its neighbors, so
        # the top-1 rated cluster IS the favored cluster; zero-weight
        # ratings (sparsified-away or pad edges) are not real favorites
        top = rating_top3_by_sort(graph, neighbor_cluster, seed, k_best=1)
        favored = jnp.where(top[1] > 0, top[0], -1)
    elif engine == "hash":
        slot_label, slot_w = hashed_rating_table(
            graph.src, neighbor_cluster, graph.edge_w, n_pad,
            cfg.num_slots, seed,
        )
        favored, fav_w = best_from_rating_table(
            slot_label, slot_w, labels, cluster_weights, graph.node_w,
            jnp.broadcast_to(max_cluster_weight, (cluster_weights.shape[0],)),
            seed, require_fit=False,
        )
        favored = jnp.where(fav_w > 0, favored, -1)
    else:
        seg_g, key_g, w_g = aggregate_by_key(
            graph.src, neighbor_cluster, graph.edge_w
        )
        favored, _ = argmax_per_segment(
            seg_g, key_g, w_g, n_pad, tie_salt=seed
        )

    fav = jnp.where(singleton & (favored >= 0), favored, -1)
    fav_c = jnp.clip(fav, 0, n_pad - 1)
    leader = jax.ops.segment_min(
        jnp.where(fav >= 0, node_ids, jnp.iinfo(jnp.int32).max),
        fav_c,
        num_segments=n_pad,
    )
    my_leader = jnp.where(fav >= 0, leader[fav_c], -1)
    joiner = (fav >= 0) & (my_leader != node_ids) & (my_leader >= 0)
    target = jnp.where(joiner, my_leader, -1)

    headroom = jnp.maximum(
        jnp.broadcast_to(max_cluster_weight, (n_pad,)).astype(ACC_DTYPE)
        - cluster_weights.astype(ACC_DTYPE),
        0,
    )
    accept = accept_prefix_by_capacity(target, node_ids, graph.node_w, headroom)
    new_labels = jnp.where(accept, target, labels)
    return new_labels, apply_move_weight_delta(
        cluster_weights, labels, target, accept, graph.node_w
    )
