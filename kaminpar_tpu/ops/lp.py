"""Bulk-synchronous size-constrained label propagation on device.

The TPU re-design of the reference's LP engine
(kaminpar-shm/label_propagation.h:83 LabelPropagation<...>).  The reference
runs an *asynchronous* LP: threads sweep shuffled chunks of nodes, rate each
node's adjacent clusters in a per-thread hash map
(find_best_cluster:461-541) and commit moves with CAS cluster-weight updates
(try_node_move:818, move_cluster_weight:2139).  Fine-grained CAS does not
map to TPUs, so this kernel makes the trade the reference's own Jet refiner
makes (refinement/jet/jet_refiner.cc:1-8): *bulk-synchronous rounds* of

  1. rate:    aggregate (node, neighbor-cluster) connection weights via the
              sorted segmented reduction in ops/segments.py;
  2. select:  per-node argmax over feasible clusters (weight cap), hashed
              random tie-breaking — the analog of find_best_cluster;
  3. commit:  capacity-respecting prefix acceptance per target cluster
              (ops/segments.accept_prefix_by_capacity), so the max cluster
              weight is *never* exceeded — stronger than the reference's
              relaxed CAS, which tolerates transient overshoot;
  4. apply:   scatter accepted labels, update cluster weights, refresh the
              active set (the analog of label_propagation.h:507-513).

Oscillation control (label_propagation.h avoids it by construction via
async updates; bulk-sync must handle it explicitly):
  * zero-gain ("tie") moves only follow a per-round hashed direction —
    of two clusters that rate equally, only the one with smaller hash may
    absorb the other, which turns 2-cycles into merges;
  * per-round random participation mask (cfg.participation < 1) — the
    bulk-sync analog of the reference's shuffled chunk scheduling
    (ChunkRandomLabelPropagation:1529), breaking symmetric flip patterns.

Whole multi-round loops run inside one jit via lax.while_loop with a
moved-count convergence test, so a full clustering is a single device
program launch.

Post-passes mirroring the reference:
  * cluster_isolated_nodes (label_propagation.h:872-917)
  * two-hop clustering of leftover singletons by favored cluster
    (label_propagation.h:919-1191)
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..graphs.csr import DeviceGraph
from .segments import (
    ACC_DTYPE,
    INT32_MIN,
    accept_prefix_by_capacity,
    aggregate_by_key,
    apply_move_weight_delta,
    argmax_per_segment,
    best_from_dense,
    best_from_rating_table,
    connection_to_label,
    connection_to_own_label,
    dense_block_ratings,
    hash_u32,
    neighbor_any_true,
    packed_afterburner_gain,
    hashed_rating_table,
    rating_top3_by_sort,
)


@dataclass(frozen=True)
class LPConfig:
    """Knobs mirroring LabelPropagationConfig (label_propagation.h:36-74)
    plus the bulk-sync-specific ones."""

    num_iterations: int = 5  # lp_clusterer.cc default
    participation: float = 0.5  # per-round node participation probability
    allow_tie_moves: bool = True
    use_active_set: bool = True
    # post-pass toggles (two_hop_strategy / isolated_nodes_strategy enums)
    two_hop: bool = True
    cluster_isolated: bool = True
    # refinement mode: labels are blocks, moves need positive gain
    refinement: bool = False
    # distributed-only: restrict joins to clusters owned by the same device
    # (LocalLPClusterer analog, kaminpar-dist/.../local_lp_clusterer.cc —
    # no cross-PE clusters, so contraction needs no label migration)
    dist_local_only: bool = False
    # rating engine: "auto" picks dense (labels = k blocks) > hash (big
    # edge lists, hashed slots, no sort) > sort (exact aggregate_by_key);
    # see ops/segments.py "Sort-free rating engines"
    rating: str = "auto"
    num_slots: int = 32  # hashed engine slots per node
    # m_pad at which "auto" switches sort -> sort2/hash
    hash_threshold: int = 1 << 21
    # sort2: how many top clusters to read per node (n-sized reads, cheap)
    topk: int = 6
    # sort2: below this m_pad, compute the own-cluster connection exactly
    # with one edge-wide pass instead of the top-K bound
    exact_wcur_threshold: int = 1 << 23


def _select_engine(
    cfg: LPConfig,
    num_clusters: int,
    m_pad: int,
    has_communities: bool = False,
) -> str:
    """Static (trace-time) rating engine choice.  sort2 (the fastest
    clustering engine — one edge gather + two sorts, no scatters) does not
    support the v-cycle community restriction, so community-constrained
    clustering falls back to the hashed engine."""
    if cfg.rating != "auto":
        if cfg.rating == "sort2" and has_communities:
            raise ValueError(
                "rating='sort2' cannot enforce the community restriction; "
                "use 'hash' or 'sort' (or rating='auto')"
            )
        return cfg.rating
    if num_clusters <= 256:
        return "dense"
    if m_pad >= cfg.hash_threshold:
        if has_communities:
            return "hash"
        # sort2 sees only the top-K clusters per node: ideal on sparse
        # fine levels (few adjacent clusters), blind on dense coarse
        # levels where nodes border hundreds of clusters — there the
        # hashed slot table (num_slots candidates + exact own-connection)
        # keeps LP converging
        avg_degree = m_pad / max(num_clusters, 1)
        return "sort2" if avg_degree <= 32 else "hash"
    return "sort"


def lp_round(
    graph: DeviceGraph,
    labels: jax.Array,
    cluster_weights: jax.Array,
    max_cluster_weight: jax.Array,
    active: jax.Array,
    salt: jax.Array,
    cfg: LPConfig,
    communities: jax.Array | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One bulk-synchronous LP round.

    Args:
      labels:            i32[n_pad] cluster id per node (clusters are node
                         ids during coarsening, block ids during refinement)
      cluster_weights:   i32[C] current weight per cluster
      max_cluster_weight:i32 scalar or i32[C] per-cluster cap
      active:            bool[n_pad] active set
      salt:              i32 per-round randomness salt

    Returns (new_labels, new_cluster_weights, new_active, num_moved).
    """
    n_pad = graph.n_pad
    C = cluster_weights.shape[0]
    cap = jnp.broadcast_to(max_cluster_weight, (C,))
    engine = _select_engine(cfg, C, graph.m_pad, communities is not None)

    # -- rate: per-node best non-own cluster under the weight cap, plus
    # the exact connection to the own cluster.  Engines with one contract
    # (see ops/segments.py "Sort-free rating engines").
    neighbor_cluster = labels[graph.dst]
    if engine == "sort2":
        # top-K clusters per node, then node-level own-exclusion +
        # feasibility fallback chain
        K = cfg.topk
        topk = rating_top3_by_sort(
            graph, neighbor_cluster, salt, k_best=K
        )
        labs = topk[0::2]
        vals = topk[1::2]
        own = labels

        # w_cur: exact when the own cluster ranks top-K or when the edge
        # list is small enough that an exact edge-wide pass is cheap;
        # otherwise bounded above by the K-th total (which UNDERestimates
        # gains, i.e. errs toward fewer moves).  Dense coarse levels have
        # small m, so they get the exact path and keep converging.
        if graph.m_pad <= cfg.exact_wcur_threshold:
            w_cur = connection_to_own_label(
                graph.src, neighbor_cluster, graph.edge_w, labels, n_pad
            )
        else:
            w_cur = jnp.where(
                labs[-1] >= 0, jnp.maximum(vals[-1], 0), 0
            )
            for lab_j, val_j in zip(reversed(labs), reversed(vals)):
                w_cur = jnp.where(lab_j == own, val_j, w_cur)

        def fits(lab):
            lab_c = jnp.clip(lab, 0, C - 1)
            return (lab >= 0) & (
                cluster_weights[lab_c].astype(ACC_DTYPE)
                + graph.node_w.astype(ACC_DTYPE)
                <= cap[lab_c]
            )

        best = jnp.full(n_pad, -1, dtype=jnp.int32)
        best_w = jnp.full(n_pad, INT32_MIN, dtype=ACC_DTYPE)
        for lab_j, val_j in zip(reversed(labs), reversed(vals)):
            ok = (lab_j != own) & fits(lab_j)
            best = jnp.where(ok, lab_j, best)
            best_w = jnp.where(ok, val_j, best_w)
    elif engine == "dense":
        conn = dense_block_ratings(
            graph.src, graph.dst, graph.edge_w, labels, n_pad, C
        )
        best, best_w, w_cur = best_from_dense(
            conn, labels, cluster_weights, graph.node_w, cap, salt,
            communities=communities,
        )
    elif engine == "hash":
        slot_label, slot_w = hashed_rating_table(
            graph.src, neighbor_cluster, graph.edge_w, n_pad,
            cfg.num_slots, salt,
        )
        best, best_w = best_from_rating_table(
            slot_label, slot_w, labels, cluster_weights, graph.node_w,
            cap, salt ^ 0x51AB, communities=communities,
        )
        w_cur = connection_to_own_label(
            graph.src, neighbor_cluster, graph.edge_w, labels, n_pad
        )
    else:  # sort (exact enumeration of every adjacent cluster)
        seg_g, key_g, w_g = aggregate_by_key(
            graph.src, neighbor_cluster, graph.edge_w
        )
        key_c = jnp.clip(key_g, 0, C - 1)
        seg_c = jnp.clip(seg_g, 0, n_pad - 1)
        fits = (
            cluster_weights[key_c].astype(ACC_DTYPE)
            + graph.node_w[seg_c].astype(ACC_DTYPE)
            <= cap[key_c]
        )
        feasible = (seg_g >= 0) & (key_g != labels[seg_c]) & fits
        if communities is not None:
            # v-cycle community restriction: a cluster label is a node id,
            # so the cluster's community is the label node's community
            feasible = feasible & (communities[key_c] == communities[seg_c])
        best, best_w = argmax_per_segment(
            seg_g, key_g, w_g, n_pad, tie_salt=salt, feasible=feasible
        )
        w_cur = connection_to_label(seg_g, key_g, w_g, labels, n_pad)

    # -- select ----------------------------------------------------------
    gain = best_w - w_cur
    tie_dir_ok = hash_u32(best, salt ^ 0x5BD1) < hash_u32(labels, salt ^ 0x5BD1)
    if cfg.refinement:
        improves = gain > 0
    else:
        improves = (gain > 0) | (
            cfg.allow_tie_moves & (gain == 0) & (best_w > 0) & tie_dir_ok
        )
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    participate = hash_u32(node_ids, salt ^ 0x27D4) < jnp.int32(
        cfg.participation * 2147483647.0
    )
    wants = (
        (best >= 0) & (best != labels) & improves & active & (node_ids < graph.n)
    )
    target = jnp.where(wants & participate, best, -1)

    if cfg.refinement:
        # afterburner (Jet's filter step, jet_refiner.cc:133-170): in a
        # bulk-synchronous round, simultaneous moves of ADJACENT nodes can
        # increase the cut even though each individual gain is positive;
        # keep only candidates whose adjusted gain stays positive.  The
        # async reference never needs this (moves see latest labels);
        # without it bulk-sync LP refinement can DOUBLE the cut.
        # `wants` is deliberately NOT masked: filtered/unsampled nodes
        # must stay in the convergence count and the active set.
        # Packed metadata keeps this at TWO edge-wide gathers (the naive
        # per-endpoint gathers were ~10x a Jet iteration at equal shape).
        candidate = target >= 0
        next_lab = jnp.where(candidate, target, labels)
        adj_gain = packed_afterburner_gain(
            graph.src, graph.dst, graph.edge_w, graph.row_ptr,
            labels, next_lab, gain, candidate, C,
        )
        target = jnp.where(candidate & (adj_gain > 0), target, -1)

    # -- commit: never exceed the cap even under simultaneous joins ------
    headroom = jnp.maximum(cap - cluster_weights.astype(ACC_DTYPE), 0)
    prio = hash_u32(node_ids, salt ^ 0x165667B1)
    accept = accept_prefix_by_capacity(target, prio, graph.node_w, headroom)

    # -- apply -----------------------------------------------------------
    new_labels = jnp.where(accept, target, labels)
    new_cluster_weights = apply_move_weight_delta(
        cluster_weights, labels, target, accept, graph.node_w
    )

    # -- active set refresh (label_propagation.h:507-513): a node is active
    # next round iff it or one of its neighbors moved this round.  In the
    # async reference this SAVES work (inactive nodes are skipped); in a
    # bulk-synchronous round every node is computed regardless, so the
    # neighbor propagation is pure overhead (an edge-wide gather+scatter,
    # the two most expensive TPU ops) — the fast engine keeps everyone
    # active and lets the num_wanting convergence test do its job
    if cfg.use_active_set and engine != "sort2":
        # one edge gather + streaming row sums (scatter-free; see
        # segments.neighbor_any_true)
        neigh_moved = neighbor_any_true(accept, graph.dst, graph.row_ptr)
        # wanting-but-unsampled (or capacity-rejected) nodes stay active;
        # otherwise the participation mask could deactivate a node that
        # still has an improving move
        new_active = accept | neigh_moved | (wants & ~accept)
    else:
        new_active = jnp.ones_like(active)

    # convergence is judged on *wanting* nodes, not sampled movers: a round
    # where the participation sample happens to move nobody must not stop
    # the loop while unsampled nodes still have improving moves
    num_wanting = jnp.sum(wants.astype(jnp.int32))
    return new_labels, new_cluster_weights, new_active, num_wanting


@partial(jax.jit, static_argnames=("cfg", "num_iterations", "has_communities"))
def _lp_cluster_impl(
    graph: DeviceGraph,
    max_cluster_weight: jax.Array,
    seed: jax.Array,
    communities: jax.Array,
    cfg: LPConfig,
    num_iterations: int | None,
    has_communities: bool,
) -> jax.Array:
    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    n_pad = graph.n_pad
    labels0 = jnp.arange(n_pad, dtype=jnp.int32)
    weights0 = graph.node_w.astype(jnp.int32)
    active0 = jnp.ones(n_pad, dtype=bool)
    comm = communities if has_communities else None

    def cond(state):
        i, _, _, _, moved = state
        return (i < iters) & (moved != 0)

    def body(state):
        i, labels, weights, active, _ = state
        salt = (seed.astype(jnp.int32) * 131071 + i * 1566083941) & 0x7FFFFFFF
        labels, weights, active, moved = lp_round(
            graph,
            labels,
            weights,
            max_cluster_weight,
            active,
            salt,
            cfg,
            communities=comm,
        )
        return (i + 1, labels, weights, active, moved)

    init = (jnp.int32(0), labels0, weights0, active0, jnp.int32(1))
    _, labels, weights, _, _ = lax.while_loop(cond, body, init)

    if not has_communities:
        # community-restricted clustering (v-cycles) skips the singleton
        # post-passes: they could merge across community boundaries
        if cfg.cluster_isolated:
            labels, weights = cluster_isolated_nodes(
                graph, labels, weights, max_cluster_weight
            )
        if cfg.two_hop:
            labels, weights = two_hop_cluster(
                graph, labels, weights, max_cluster_weight, seed, cfg
            )
    return labels


def lp_cluster(
    graph: DeviceGraph,
    max_cluster_weight: jax.Array,
    seed: jax.Array,
    cfg: LPConfig = LPConfig(),
    num_iterations: int | None = None,
    communities: jax.Array | None = None,
) -> jax.Array:
    """Size-constrained LP clustering (analog of LPClustering::compute_
    clustering, lp_clusterer.cc:90-110): every node starts as a singleton,
    runs `num_iterations` rounds or until no node moves, then clusters
    isolated nodes and two-hop-merges leftover singletons.

    `communities` (optional i32[n_pad]) restricts clustering to within
    communities — nodes only join clusters whose label node shares their
    community (Clusterer::set_communities analog, used by v-cycles).

    Returns i32[n_pad] cluster labels (values are node ids; pad slots keep
    their own id)."""
    has_comm = communities is not None
    if communities is None:
        communities = jnp.zeros(graph.n_pad, dtype=jnp.int32)
    return _lp_cluster_impl(
        graph,
        max_cluster_weight,
        seed,
        communities,
        cfg,
        num_iterations,
        has_comm,
    )


@partial(jax.jit, static_argnames=("cfg",))
def _lp_refine_round_launch(graph, part, bw, max_block_weights, active,
                            salt, cfg: LPConfig):
    return lp_round(graph, part, bw, max_block_weights, active, salt, cfg)


def lp_refine(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    cfg: LPConfig = LPConfig(refinement=True),
    num_iterations: int | None = None,
) -> jax.Array:
    """LP refinement entry point.  Above MAX_FUSED_EDGE_SLOTS a
    multi-round fused launch runs for minutes and reproducibly kills the
    TPU worker (same failure mode Jet's chunking guards against), so
    huge graphs run one round per launch — keeping the fused path's
    active set and moved==0 convergence exit across launches."""
    from .segments import MAX_FUSED_EDGE_SLOTS

    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    if not cfg.refinement:
        # normalize once for BOTH launch strategies so the chunked path
        # never runs with clustering semantics (tie moves, no positive-gain
        # restriction); replace() preserves the caller's engine settings
        cfg = replace(cfg, allow_tie_moves=False, refinement=True)
    if graph.src.shape[0] > MAX_FUSED_EDGE_SLOTS and iters > 1:
        part = jnp.clip(partition, 0, k - 1).astype(jnp.int32)
        bw = jax.ops.segment_sum(
            graph.node_w.astype(ACC_DTYPE), part, num_segments=k
        ).astype(jnp.int32)
        active = jnp.ones(graph.n_pad, dtype=bool)
        for i in range(iters):
            # equivalent to the fused while_loop's traced int32-wraparound
            # `i * 1566083941`: the final & 0x7FFFFFFF drops bit 31, and
            # bit 31 of an addend cannot reach lower sum bits — so masking
            # the python product to 31 bits visits identical states
            off = jnp.int32((i * 1566083941) & 0x7FFFFFFF)
            salt = (jnp.asarray(seed, jnp.int32) * 92821 + off) & 0x7FFFFFFF
            part, bw, active, moved = _lp_refine_round_launch(
                graph, part, bw, max_block_weights, active, salt, cfg
            )
            if int(moved) == 0:
                break
        return part
    return _lp_refine_fused(
        graph, partition, k, max_block_weights, seed, cfg, iters
    )


@partial(jax.jit, static_argnames=("cfg", "k", "num_iterations"))
def _lp_refine_fused(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    cfg: LPConfig = LPConfig(refinement=True),
    num_iterations: int | None = None,
) -> jax.Array:
    """LP refinement (analog of LabelPropagationRefiner,
    kaminpar-shm/refinement/lp/lp_refiner.cc): the LP kernel with clusters
    fixed to the k blocks, moves restricted to strictly positive gain under
    the per-block max weights.  Returns the refined partition."""
    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    if not cfg.refinement:
        cfg = replace(cfg, allow_tie_moves=False, refinement=True)
    n_pad = graph.n_pad
    part0 = jnp.clip(partition, 0, k - 1).astype(jnp.int32)
    bw0 = jax.ops.segment_sum(
        graph.node_w.astype(ACC_DTYPE), part0, num_segments=k
    ).astype(jnp.int32)
    active0 = jnp.ones(n_pad, dtype=bool)

    def cond(state):
        i, _, _, _, moved = state
        return (i < iters) & (moved != 0)

    def body(state):
        i, part, bw, active, _ = state
        salt = (seed.astype(jnp.int32) * 92821 + i * 1566083941) & 0x7FFFFFFF
        part, bw, active, moved = lp_round(
            graph, part, bw, max_block_weights, active, salt, cfg
        )
        return (i + 1, part, bw, active, moved)

    init = (jnp.int32(0), part0, bw0, active0, jnp.int32(1))
    _, part, _, _, _ = lax.while_loop(cond, body, init)
    return part


def cluster_isolated_nodes(
    graph: DeviceGraph,
    labels: jax.Array,
    cluster_weights: jax.Array,
    max_cluster_weight: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Merge isolated singleton nodes into shared clusters under the weight
    cap (label_propagation.h:872-917).

    Isolated nodes are ordered by id; node i's tentative bin is
    floor(prefix_weight / cap); within each bin the capacity-respecting
    prefix pass rejects overflow (exactness), rejected nodes stay singleton.
    The first member of each bin is its leader; members adopt the leader's
    label."""
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    is_real = node_ids < graph.n
    deg = graph.degrees
    iso = (deg == 0) & is_real & (labels == node_ids)

    cap = jnp.maximum(jnp.broadcast_to(max_cluster_weight, ()).astype(ACC_DTYPE), 1)
    w = jnp.where(iso, graph.node_w, 0).astype(ACC_DTYPE)
    cum_before = jnp.cumsum(w) - w
    bin_id = jnp.where(iso, (cum_before // cap).astype(jnp.int32), -1)

    # leader of each bin = first isolated node in it
    first_in_bin = jax.ops.segment_min(
        jnp.where(iso, node_ids, jnp.iinfo(jnp.int32).max),
        jnp.clip(bin_id, 0, n_pad - 1),
        num_segments=n_pad,
    )
    leader = jnp.where(iso, first_in_bin[jnp.clip(bin_id, 0, n_pad - 1)], -1)
    # joiners (non-leaders) move into the leader's cluster, capacity-checked
    joiner = iso & (leader != node_ids) & (leader >= 0)
    target = jnp.where(joiner, leader, -1)
    headroom = jnp.maximum(
        jnp.broadcast_to(max_cluster_weight, (n_pad,)).astype(ACC_DTYPE)
        - cluster_weights.astype(ACC_DTYPE),
        0,
    )
    accept = accept_prefix_by_capacity(
        target, node_ids, graph.node_w, headroom
    )
    new_labels = jnp.where(accept, target, labels)
    return new_labels, apply_move_weight_delta(
        cluster_weights, labels, target, accept, graph.node_w
    )


def two_hop_cluster(
    graph: DeviceGraph,
    labels: jax.Array,
    cluster_weights: jax.Array,
    max_cluster_weight: jax.Array,
    seed: jax.Array,
    cfg: LPConfig = LPConfig(),
) -> Tuple[jax.Array, jax.Array]:
    """Two-hop clustering of leftover singletons (label_propagation.h:919-
    1191): singleton nodes that share the same *favored cluster* (their
    best-rated cluster, ignoring the weight cap) are merged with each other
    — they are two hops apart through that cluster.  The smallest singleton
    id per favored cluster becomes the leader; the rest join it under the
    weight cap."""
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    is_real = node_ids < graph.n
    singleton = (
        (labels == node_ids)
        & (cluster_weights[jnp.clip(labels, 0, n_pad - 1)] == graph.node_w)
        & is_real
        & (graph.degrees > 0)
    )

    # favored cluster = unconstrained best-rated cluster (same engine
    # dispatch as lp_round; a singleton's own label never appears among
    # its neighbors' labels, so own-exclusion is harmless here)
    neighbor_cluster = labels[graph.dst]
    engine = _select_engine(cfg, cluster_weights.shape[0], graph.m_pad)
    if engine == "sort2":
        # a singleton's own label never appears among its neighbors, so
        # the top-1 rated cluster IS the favored cluster; zero-weight
        # ratings (sparsified-away or pad edges) are not real favorites
        top = rating_top3_by_sort(graph, neighbor_cluster, seed, k_best=1)
        favored = jnp.where(top[1] > 0, top[0], -1)
    elif engine == "hash":
        slot_label, slot_w = hashed_rating_table(
            graph.src, neighbor_cluster, graph.edge_w, n_pad,
            cfg.num_slots, seed,
        )
        favored, fav_w = best_from_rating_table(
            slot_label, slot_w, labels, cluster_weights, graph.node_w,
            jnp.broadcast_to(max_cluster_weight, (cluster_weights.shape[0],)),
            seed, require_fit=False,
        )
        favored = jnp.where(fav_w > 0, favored, -1)
    else:
        seg_g, key_g, w_g = aggregate_by_key(
            graph.src, neighbor_cluster, graph.edge_w
        )
        favored, _ = argmax_per_segment(
            seg_g, key_g, w_g, n_pad, tie_salt=seed
        )

    fav = jnp.where(singleton & (favored >= 0), favored, -1)
    fav_c = jnp.clip(fav, 0, n_pad - 1)
    leader = jax.ops.segment_min(
        jnp.where(fav >= 0, node_ids, jnp.iinfo(jnp.int32).max),
        fav_c,
        num_segments=n_pad,
    )
    my_leader = jnp.where(fav >= 0, leader[fav_c], -1)
    joiner = (fav >= 0) & (my_leader != node_ids) & (my_leader >= 0)
    target = jnp.where(joiner, my_leader, -1)

    headroom = jnp.maximum(
        jnp.broadcast_to(max_cluster_weight, (n_pad,)).astype(ACC_DTYPE)
        - cluster_weights.astype(ACC_DTYPE),
        0,
    )
    accept = accept_prefix_by_capacity(target, node_ids, graph.node_w, headroom)
    new_labels = jnp.where(accept, target, labels)
    return new_labels, apply_move_weight_delta(
        cluster_weights, labels, target, accept, graph.node_w
    )
