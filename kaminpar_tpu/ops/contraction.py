"""Cluster contraction on device.

TPU re-design of kaminpar-shm/coarsening/contraction/ (BUFFERED/UNBUFFERED
cluster contraction, cluster_contraction.h:50-59 contract_clustering): given
per-node cluster labels, build the coarse graph whose nodes are clusters and
whose edges aggregate inter-cluster edge weights.

The reference remaps cluster ids to dense coarse ids with a parallel leader
mapping + prefix sum (cluster_contraction_preprocessing.cc:17,69
fill_leader_mapping), then deduplicates per-coarse-node edges through
per-thread rating maps (unbuffered_cluster_contraction.cc).  The TPU version
is two fused array programs around one host sync:

  part 1 (jit, fine shapes):  scatter-mark used labels -> prefix-sum dense
      ids (compact_unique), coarse node weights by segment sum, coarse edge
      endpoints (cu, cv) = (cmap[src], cmap[dst]) with self-loops and pad
      edges routed to a trailing sentinel, then one sorted segmented
      aggregation (ops/segments.aggregate_by_key) that yields the
      deduplicated coarse edge list in CSR order.

  host: read the coarse node/edge counts (the one unavoidable device->host
      sync per level — the multilevel driver needs them to pick the next
      shape bucket, SURVEY.md §7 'hard parts').

  part 2 (jit, coarse shapes): slice/pad the aggregated groups into the
      coarse shape bucket and rebuild row_ptr by counting sort.

Projection between levels (cluster_contraction.h:22-32 project_up/down) is
a single gather through the stored fine->coarse map.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..graphs.csr import DeviceGraph, WEIGHT_DTYPE
from ..caching import pad_size
from .segments import ACC_DTYPE, aggregate_by_key


@jax.tree_util.register_dataclass
@dataclass
class CoarseGraph:
    """A coarse graph plus the fine->coarse projection map
    (analog of CoarseGraph in cluster_contraction.h:22-32)."""

    graph: DeviceGraph
    cmap: jax.Array  # i32[n_pad_fine]: coarse node id of each fine node

    def project_up(self, coarse_partition: jax.Array) -> jax.Array:
        """Coarse partition -> fine partition (project_up)."""
        return coarse_partition[self.cmap]

    def project_down(self, fine_partition: jax.Array) -> jax.Array:
        """Fine partition -> coarse partition by representative gather
        (project_down; consistent only if the fine partition is constant
        per cluster)."""
        n_pad_c = self.graph.n_pad
        first_fine = jax.ops.segment_min(
            jnp.arange(self.cmap.shape[0], dtype=jnp.int32),
            self.cmap,
            num_segments=n_pad_c,
        )
        safe = jnp.clip(first_fine, 0, self.cmap.shape[0] - 1)
        return fine_partition[safe]


@jax.jit
def _contract_part1(graph: DeviceGraph, labels: jax.Array, plans=None):
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    is_real = node_ids < graph.n

    # dense coarse ids (fill_leader_mapping + prefix sum analog)
    lab = jnp.clip(labels, 0, n_pad - 1)
    used = jnp.zeros(n_pad, dtype=jnp.int32).at[lab].max(
        is_real.astype(jnp.int32)
    )
    rank = jnp.cumsum(used) - used
    cmap = jnp.where(is_real, rank[lab], -1).astype(jnp.int32)
    # coarse-node count <= n, ID domain  # tpulint: disable=R3
    c_n = jnp.sum(used, dtype=jnp.int32)

    # coarse node weights over fine slots
    c_node_w = jax.ops.segment_sum(
        jnp.where(is_real, graph.node_w, 0).astype(ACC_DTYPE),
        jnp.clip(cmap, 0, n_pad - 1),
        num_segments=n_pad,
    ).astype(WEIGHT_DTYPE)

    # coarse edges: route self-loops and pad edges to a trailing
    # sentinel.  aggregate_by_key SORTS by (cu, cv), so slot order is
    # free — with level plans, cmap[dst] runs through the lane-routed
    # gather and cmap itself provides the validity check (-1 marks
    # non-real endpoints, including every pad slot via owner n_pad-1).
    if plans is not None:
        from .lane_gather import INTERPRET, lane_gather

        sentinel = jnp.int32(n_pad)
        cu0 = cmap[plans.src_idx]
        cv0 = lane_gather(cmap, plans.plan, interpret=INTERPRET)
        valid = (cu0 != cv0) & (cu0 >= 0) & (cv0 >= 0)
        cu = jnp.where(valid, cu0, sentinel)
        cv = jnp.where(valid, cv0, sentinel)
        w = jnp.where(valid, plans.edge_w, 0)
    else:
        sentinel = jnp.int32(n_pad)
        cu = jnp.where(graph.src < graph.n, cmap[jnp.clip(graph.src, 0, n_pad - 1)], sentinel)
        cv = jnp.where(graph.dst < graph.n, cmap[jnp.clip(graph.dst, 0, n_pad - 1)], sentinel)
        valid = (cu != cv) & (cu < sentinel) & (cv < sentinel)
        cu = jnp.where(valid, cu, sentinel)
        cv = jnp.where(valid, cv, sentinel)
        w = jnp.where(valid, graph.edge_w, 0)

    cu_g, cv_g, w_g = aggregate_by_key(cu, cv, w)
    group_valid = (cu_g >= 0) & (cu_g < sentinel)
    # coarse-edge count <= m_pad < 2^31 (device layout)  # tpulint: disable=R3
    c_m = jnp.sum(group_valid, dtype=jnp.int32)
    return cmap, c_n, c_node_w, cu_g, cv_g, w_g, group_valid, c_m


@partial(jax.jit, static_argnames=("n_pad_c", "m_pad_c"))
def _contract_part2(
    n_pad_c: int,
    m_pad_c: int,
    cmap,
    c_n,
    c_node_w,
    cu_g,
    cv_g,
    w_g,
    group_valid,
    c_m,
):
    pad_node = n_pad_c - 1
    m_pad_f = cu_g.shape[0]

    def fit_edges(x, fill):
        if m_pad_c <= m_pad_f:
            return x[:m_pad_c]
        return jnp.concatenate(
            [x, jnp.full(m_pad_c - m_pad_f, fill, dtype=x.dtype)]
        )

    slot = jnp.arange(m_pad_c, dtype=jnp.int32)
    in_range = slot < c_m
    src_c = jnp.where(in_range, fit_edges(cu_g, 0), pad_node).astype(jnp.int32)
    dst_c = jnp.where(in_range, fit_edges(cv_g, 0), pad_node).astype(jnp.int32)
    w_c = jnp.where(in_range, fit_edges(w_g, 0), 0).astype(WEIGHT_DTYPE)

    counts = jax.ops.segment_sum(
        in_range.astype(jnp.int32),
        jnp.clip(src_c, 0, n_pad_c - 1),
        num_segments=n_pad_c,
    )
    # pad-node slot may have absorbed counts from pad edges; real coarse
    # nodes are < c_n so zero counts beyond c_n
    counts = jnp.where(jnp.arange(n_pad_c) < c_n, counts, 0)
    row_ptr = jnp.concatenate(
        # row_ptr tops out at m_pad < 2^31 (device layout contract);
        # host xadj stays int64  # tpulint: disable=R3
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )

    n_pad_f = c_node_w.shape[0]

    def fit_nodes(x, fill):
        if n_pad_c <= n_pad_f:
            return x[:n_pad_c]
        return jnp.concatenate(
            [x, jnp.full(n_pad_c - n_pad_f, fill, dtype=x.dtype)]
        )

    node_w_c = jnp.where(
        jnp.arange(n_pad_c) < c_n, fit_nodes(c_node_w, 0), 0
    ).astype(WEIGHT_DTYPE)
    cmap_final = jnp.where(cmap >= 0, cmap, pad_node).astype(jnp.int32)

    coarse = DeviceGraph(
        row_ptr=row_ptr,
        src=src_c,
        dst=dst_c,
        edge_w=w_c,
        node_w=node_w_c,
        n=c_n.astype(jnp.int32),
        m=c_m.astype(jnp.int32),
    )
    return coarse, cmap_final


def contract_clustering(
    graph: DeviceGraph, labels: jax.Array
) -> Tuple[CoarseGraph, int, int]:
    """Contract `labels` over `graph`; returns (CoarseGraph, c_n, c_m).

    Two device programs around one host sync for the coarse sizes (see
    module docstring).  The coarse graph lands in pad_size shape buckets so
    repeated contractions reuse compiled executables.
    """
    # `device-oom` chaos injection point (contraction mints the largest
    # fresh buffers of a level) — handled by the recovery ladder
    from ..resilience import maybe_inject

    maybe_inject("device-oom")
    from .lane_gather import maybe_edge_plans

    cmap, c_n, c_node_w, cu_g, cv_g, w_g, group_valid, c_m = _contract_part1(
        graph, labels, maybe_edge_plans(graph)  # eager: host readbacks
    )
    from ..graphs.csr import shape_floors

    c_n_i, c_m_i = int(c_n), int(c_m)
    n_floor, m_floor = shape_floors()
    n_pad_c = pad_size(c_n_i + 1, n_floor)
    m_pad_c = pad_size(max(c_m_i, 1), m_floor)
    from ..caching import record_padding

    record_padding(n=c_n_i + 1, n_pad=n_pad_c, m=c_m_i, m_pad=m_pad_c)
    coarse, cmap_final = _contract_part2(
        n_pad_c, m_pad_c, cmap, c_n, c_node_w, cu_g, cv_g, w_g, group_valid, c_m
    )
    return CoarseGraph(graph=coarse, cmap=cmap_final), c_n_i, c_m_i
