"""Named presets (analog of kaminpar-shm/presets.cc:18-100).

Each preset builds a fully-populated Context; values mirror the reference's
defaults (presets.cc:102-301) where the corresponding knob exists in the TPU
design.  Reference-only knobs that have no TPU analog (e.g. per-thread
rating-map implementation choices) are intentionally absent — the TPU
equivalents are the bulk-sync LP knobs on LabelPropagationContext.
"""

from __future__ import annotations

from typing import Dict, Set

from .context import (
    ClusterWeightLimit,
    Context,
    PartitioningMode,
    RefinementAlgorithm,
    TwoHopStrategy,
)


def create_default_context() -> Context:
    """presets.cc:102-301 (deep multilevel, LP coarsening) — with two
    TPU-first deviations from the reference's default, both measured on
    RMAT workloads against the reference binary:

      * Jet instead of LP as the default refiner.  The reference's LP
        refiner is asynchronous (moves see the latest labels); the
        bulk-synchronous port needs Jet's afterburner-filtered move
        selection to avoid adjacent-move conflicts, and Jet IS that
        algorithm (jet_refiner.cc:1-8 makes the same argument for GPUs).
        Balancer+LP stays available via the explicit algorithm list.

      * refine_after_extending_partition defaults ON: k-doubling
        extensions otherwise land unrefined on the finest levels, which
        measurably dominates the final cut (together these two flips take
        the RMAT bench cut from ~1.28x of the reference binary to ~0.84x
        — better than the reference)."""
    ctx = Context(preset_name="default")
    # Jet then an afterburned-LP polish pass; two Jet rounds on the
    # finest level.  Measured on the medium RMAT bench (both seeds):
    # ~0.8% lower cut than Jet-only at marginal extra device time.
    ctx.refinement.algorithms = [
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
        RefinementAlgorithm.JET,
        RefinementAlgorithm.LABEL_PROPAGATION,
    ]
    ctx.refinement.jet.num_rounds_on_fine_level = 2
    ctx.partitioning.refine_after_extending_partition = True
    return ctx


def create_fast_context() -> Context:
    """presets.cc:301-309: single LP iteration, single IP repetition."""
    ctx = create_default_context()
    ctx.preset_name = "fast"
    ctx.coarsening.clustering.lp.num_iterations = 1
    ctx.initial_partitioning.pool.min_num_repetitions = 1
    ctx.initial_partitioning.pool.min_num_non_adaptive_repetitions = 1
    ctx.initial_partitioning.pool.max_num_repetitions = 1
    ctx.partitioning.light_intermediate_refinement = True
    return ctx


def create_strong_context() -> Context:
    """presets.cc:311-324: adds k-way FM between refinement and final
    balancing (Jet plays the reference's LP slot, see default).  The
    localized batch FM (native/fm.cpp) runs on the finest levels,
    ALTERNATED with Jet — FM escapes Jet's bulk-move local optimum, Jet
    then re-polishes the FM result.  Measured variants on the medium
    bench (docs/performance.md): jet-fm-jet-fm with 3 FM passes and
    light intermediate refinement cuts 2.0% below default (single
    jet+fm: 1.7%; 6 passes or FM on intermediate extensions buy nothing
    further; a doubled Jet budget instead buys nothing at all)."""
    ctx = create_default_context()
    ctx.preset_name = "strong"
    ctx.refinement.algorithms = [
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
        RefinementAlgorithm.JET,
        RefinementAlgorithm.GREEDY_FM,
        RefinementAlgorithm.JET,
        RefinementAlgorithm.GREEDY_FM,
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
    ]
    # intermediate extensions get single-round Jet and skip FM; the
    # final extension's refine at each level is the real polish
    ctx.partitioning.light_intermediate_refinement = True
    return ctx


def create_largek_context() -> Context:
    """presets.cc:326-334: fewer IP repetitions for huge k.  Refinement
    avoids every dense (n, k) structure — Jet's connection table cannot
    exist at the reference's k=30,000 claim (README.MD:17); LP refinement
    rates through the sort engine and the balancers switch to edge
    aggregation above ops/balancer.BALANCER_DENSE_MAX_K."""
    ctx = create_default_context()
    ctx.preset_name = "largek"
    ctx.initial_partitioning.pool.min_num_repetitions = 4
    ctx.initial_partitioning.pool.min_num_non_adaptive_repetitions = 2
    ctx.initial_partitioning.pool.max_num_repetitions = 4
    ctx.refinement.algorithms = [
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
        RefinementAlgorithm.LABEL_PROPAGATION,
    ]
    return ctx


def create_largek_fast_context() -> Context:
    ctx = create_largek_context()
    ctx.preset_name = "largek-fast"
    pool = ctx.initial_partitioning.pool
    pool.min_num_repetitions = 2
    pool.min_num_non_adaptive_repetitions = 1
    pool.max_num_repetitions = 2
    pool.enable_ggg_bipartitioner = False
    pool.refinement.disabled = True
    pool.refinement.num_iterations = 1
    return ctx


def create_largek_strong_context() -> Context:
    ctx = create_largek_context()
    ctx.preset_name = "largek-strong"
    ctx.refinement.algorithms = [
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
        RefinementAlgorithm.LABEL_PROPAGATION,
        RefinementAlgorithm.GREEDY_FM,
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
    ]
    return ctx


def create_jet_context(rounds: int = 1) -> Context:
    """presets.cc:372-391: Jet instead of LP refinement — the preset most
    aligned with the TPU execution model."""
    ctx = create_default_context()
    ctx.preset_name = "jet" if rounds == 1 else f"{rounds}xjet"
    ctx.refinement.algorithms = [
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
        RefinementAlgorithm.JET,
    ]
    if rounds > 1:
        jet = ctx.refinement.jet
        jet.num_rounds_on_coarse_level = rounds
        jet.num_rounds_on_fine_level = rounds
        jet.initial_gain_temp_on_coarse_level = 0.75
        jet.initial_gain_temp_on_fine_level = 0.75
        jet.final_gain_temp_on_coarse_level = 0.25
        jet.final_gain_temp_on_fine_level = 0.25
    return ctx


def create_noref_context() -> Context:
    ctx = create_default_context()
    ctx.preset_name = "noref"
    ctx.refinement.algorithms = []
    return ctx


def create_vcycle_context(restrict_refinement: bool = False) -> Context:
    """presets.cc:422-436."""
    ctx = create_default_context()
    ctx.preset_name = "restricted-vcycle" if restrict_refinement else "vcycle"
    ctx.partitioning.mode = PartitioningMode.VCYCLE
    if restrict_refinement:
        ctx.partitioning.restrict_vcycle_refinement = True
        ctx.refinement.algorithms = [RefinementAlgorithm.LABEL_PROPAGATION]
    return ctx


def _terapartify(ctx: Context) -> Context:
    """presets.cc terapartify_context: enable compressed-graph mode."""
    ctx.compression.enabled = True
    ctx.preset_name = "terapart"
    return ctx


def create_terapart_context() -> Context:
    return _terapartify(create_default_context())


def create_terapart_strong_context() -> Context:
    ctx = _terapartify(create_strong_context())
    ctx.preset_name = "terapart-strong"
    return ctx


def create_terapart_largek_context() -> Context:
    ctx = _terapartify(create_largek_context())
    ctx.preset_name = "terapart-largek"
    ctx.coarsening.clustering.forced_kc_level = True
    return ctx


def create_esa21_smallk_context() -> Context:
    """presets.cc create_esa21_smallk_context: the ESA'21 configuration.
    The reference switches to BUFFERED contraction + single-phase LP; the
    TPU kernels have one contraction and one LP implementation, so this is
    the default pipeline under the historical name."""
    ctx = create_default_context()
    ctx.preset_name = "esa21-smallk"
    return ctx


def create_esa21_largek_context() -> Context:
    ctx = create_esa21_smallk_context()
    ctx.preset_name = "esa21-largek"
    ctx.initial_partitioning.pool.min_num_repetitions = 4
    ctx.initial_partitioning.pool.min_num_non_adaptive_repetitions = 2
    ctx.initial_partitioning.pool.max_num_repetitions = 4
    return ctx


def create_esa21_largek_fast_context() -> Context:
    ctx = create_esa21_largek_context()
    ctx.preset_name = "esa21-largek-fast"
    pool = ctx.initial_partitioning.pool
    pool.min_num_repetitions = 2
    pool.min_num_non_adaptive_repetitions = 1
    pool.max_num_repetitions = 2
    return ctx


def create_esa21_strong_context() -> Context:
    ctx = create_esa21_smallk_context()
    ctx.preset_name = "esa21-strong"
    ctx.refinement.algorithms = [
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
        RefinementAlgorithm.LABEL_PROPAGATION,
        RefinementAlgorithm.GREEDY_FM,
        RefinementAlgorithm.OVERLOAD_BALANCER,
        RefinementAlgorithm.UNDERLOAD_BALANCER,
    ]
    return ctx


def create_linear_time_kway_context() -> Context:
    """presets.cc create_linear_time_kway_context: mtkahypar-kway with
    sparsification clustering (linear-time MGP, arXiv 2504.17615)."""
    from .context import CoarseningAlgorithm

    ctx = create_mtkahypar_kway_context()
    ctx.preset_name = "linear-time-kway"
    ctx.coarsening.algorithm = CoarseningAlgorithm.SPARSIFICATION_CLUSTERING
    return ctx


def create_mtkahypar_kway_context() -> Context:
    """presets.cc:488-499: Mt-KaHyPar-style coarsening + direct k-way."""
    ctx = create_default_context()
    ctx.preset_name = "mtkahypar-kway"
    cl = ctx.coarsening.clustering
    cl.lp.num_iterations = 1
    cl.cluster_weight_limit = ClusterWeightLimit.BLOCK_WEIGHT
    cl.cluster_weight_multiplier = 1.0 / 160.0
    cl.shrink_factor = 2.5
    cl.lp.two_hop_strategy = TwoHopStrategy.CLUSTER
    ctx.coarsening.contraction_limit = 160
    ctx.partitioning.mode = PartitioningMode.KWAY
    return ctx


_PRESETS = {
    "default": create_default_context,
    "fast": create_fast_context,
    "strong": create_strong_context,
    "fm": create_strong_context,
    "largek": create_largek_context,
    "largek-fast": create_largek_fast_context,
    "largek-strong": create_largek_strong_context,
    "terapart": create_terapart_context,
    "terapart-strong": create_terapart_strong_context,
    "terapart-largek": create_terapart_largek_context,
    "jet": create_jet_context,
    "4xjet": lambda: create_jet_context(4),
    "noref": create_noref_context,
    "vcycle": lambda: create_vcycle_context(False),
    "restricted-vcycle": lambda: create_vcycle_context(True),
    "esa21": create_esa21_smallk_context,
    "esa21-smallk": create_esa21_smallk_context,
    "esa21-largek": create_esa21_largek_context,
    "esa21-largek-fast": create_esa21_largek_fast_context,
    "esa21-strong": create_esa21_strong_context,
    "diss": create_esa21_smallk_context,
    "diss-smallk": create_esa21_smallk_context,
    "diss-largek": create_esa21_largek_context,
    "diss-largek-fast": create_esa21_largek_fast_context,
    "diss-strong": create_esa21_strong_context,
    "mtkahypar-kway": create_mtkahypar_kway_context,
    "linear-time-kway": create_linear_time_kway_context,
}


def create_context_by_preset_name(name: str) -> Context:
    """presets.cc:18-73."""
    if name not in _PRESETS:
        raise ValueError(
            f"invalid preset name: {name!r} (available: {sorted(_PRESETS)})"
        )
    return _PRESETS[name]()


def get_preset_names() -> Set[str]:
    """presets.cc:76-99."""
    return set(_PRESETS)
