"""tpulint rule implementations (R1-R5).

Each rule documents the incident that motivated it (VERDICT/ADVICE round
5) next to the pattern it matches; docs/static_analysis.md is the
operator-facing version.  All rules run in one AST walk that maintains
the lexical context stacks (enclosing function, loop depth, telemetry
span scopes).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .callgraph import (  # shared hazard surfaces (bottom layer)
    DEVICE_QUERIES,
    R6_METHODS,
    R6_QUERIES,
)
from .engine import Finding, ModuleContext, _is_jit_decorator

# R3: reductions whose accumulator width the dtypes.py policy owns.
ACC_CALLS = frozenset(
    {"cumsum", "sum", "segment_sum", "bincount", "prod", "dot", "einsum"}
)
INT32_NAMES = frozenset({"jax.numpy.int32", "numpy.int32"})


def _terminal_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _mentions_jax(node: ast.AST, ctx: ModuleContext) -> bool:
    """True when the subtree references anything under the jax package."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            q = ctx.qualname(sub)
            if q and (q == "jax" or q.startswith("jax.")):
                return True
    return False


def _is_int32(node: ast.AST, ctx: ModuleContext) -> bool:
    q = ctx.qualname(node)
    if q in INT32_NAMES:
        return True
    return isinstance(node, ast.Constant) and node.value == "int32"


def _is_span_scope_item(item: ast.withitem, ctx: ModuleContext) -> bool:
    """`with scoped_timer(...)` / `with <timer>.scope(...)` — a telemetry
    span scope.  Scopes that declare sync= measure their own host sync
    and are exempt from R1."""
    call = item.context_expr
    if not isinstance(call, ast.Call):
        return False
    name = _terminal_name(call.func)
    if name not in ("scoped_timer", "scope"):
        return False
    return not any(kw.arg == "sync" for kw in call.keywords)


class _RuleWalker(ast.NodeVisitor):
    def __init__(self, ctx: ModuleContext) -> None:
        self.ctx = ctx
        self.findings: List[Finding] = []
        self.func_stack: List[ast.AST] = []
        self.class_stack: List[str] = []
        self.loop_depth = 0
        self.span_depth = 0

    # -- helpers ----------------------------------------------------------

    def _symbol(self) -> str:
        if self.func_stack:
            return ".".join(
                f.name for f in self.func_stack
                if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
            )
        return "<module>"

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        self.findings.append(
            Finding(
                path=self.ctx.path,
                rule=rule,
                line=line,
                col=getattr(node, "col_offset", 0),
                symbol=self._symbol(),
                message=message,
                code=self.ctx.line_text(line),
            )
        )

    def _in_jit(self) -> bool:
        return bool(
            self.func_stack
            and self.func_stack[-1] in self.ctx.jit_reachable
        )

    def _r1_scope(self) -> Optional[str]:
        """Why R1 applies here (None when it does not)."""
        if self._in_jit():
            return "jit-reachable code"
        if self.span_depth > 0:
            return "a telemetry span scope"
        return None

    # -- structure visitors ------------------------------------------------

    def _visit_function(self, node) -> None:
        # R4: a jit-decorated def inside a loop mints a fresh traced
        # callable per iteration — the jit cache keys on function
        # identity, so every iteration recompiles.
        if self.loop_depth and any(
            _is_jit_decorator(d, self.ctx) for d in node.decorator_list
        ):
            self._emit(
                "R4", node,
                f"jit-decorated function '{node.name}' defined inside a "
                "loop retraces every iteration; hoist the definition",
            )
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda is a deferred thunk: the checkpoint barrier's
        # `payload=` and dist_lp's `materialize=` hooks run it outside
        # the hot path (or never), so its body is not part of the
        # enclosing span.  A lambda invoked in place escapes — a
        # documented blind spot (docs/static_analysis.md#call-graph).
        saved = self.span_depth
        self.span_depth = 0
        self.generic_visit(node)
        self.span_depth = saved

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def visit_With(self, node: ast.With) -> None:
        spans = sum(
            1 for item in node.items if _is_span_scope_item(item, self.ctx)
        )
        for item in node.items:
            self.visit(item)
        self.span_depth += 1 if spans else 0
        for stmt in node.body:
            self.visit(stmt)
        self.span_depth -= 1 if spans else 0

    def _visit_loop(self, node) -> None:
        # loop headers (iter/test) are visited at the current depth
        for fname, value in ast.iter_fields(node):
            if fname in ("body", "orelse"):
                continue
            if isinstance(value, ast.AST):
                self.visit(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.AST):
                        self.visit(v)
        self.loop_depth += 1
        for stmt in list(node.body) + list(node.orelse):
            self.visit(stmt)
        self.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    def visit_While(self, node: ast.While) -> None:
        self._check_branch_on_tracer(node, "while")
        self._visit_loop(node)

    def visit_If(self, node: ast.If) -> None:
        self._check_branch_on_tracer(node, "if")
        self.generic_visit(node)

    def _check_branch_on_tracer(self, node, kw: str) -> None:
        scope = self._r1_scope()
        if scope is None or not self._in_jit():
            # span scopes run un-traced python; branching there is fine
            return
        test = node.test
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) and _mentions_jax(sub.func, self.ctx):
                self._emit(
                    "R1", node,
                    f"python `{kw}` on a traced jax expression inside "
                    f"{scope}: forces a host sync (or a trace error); "
                    "use lax.cond/jnp.where",
                )
                return

    # -- call-site rules ---------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        ctx = self.ctx
        q = ctx.qualname(node.func)
        scope = self._r1_scope()

        # R1a: .item() is an unconditional device->host sync
        if (
            scope is not None
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            self._emit(
                "R1", node,
                f".item() inside {scope} blocks on the device; hoist the "
                "readback out of the hot path",
            )

        # R1b: int()/float()/bool() of a jax expression
        if (
            scope is not None
            and isinstance(node.func, ast.Name)
            and node.func.id in ("int", "float", "bool")
            and node.func.id not in ctx.aliases
            and node.args
            and _mentions_jax(node.args[0], ctx)
        ):
            self._emit(
                "R1", node,
                f"{node.func.id}() of a jax value inside {scope} "
                "host-syncs; keep the value on device or hoist the "
                "readback",
            )

        # R1c: np.asarray/np.array of a non-literal inside a hot scope
        if (
            scope is not None
            and q in ("numpy.asarray", "numpy.array")
            and node.args
            and not isinstance(
                node.args[0], (ast.List, ast.Tuple, ast.Constant)
            )
        ):
            self._emit(
                "R1", node,
                f"{q}() inside {scope} copies device data to host "
                "synchronously; stage the transfer outside the scope",
            )

        # R2: device/backend discovery outside the lazy gate
        if q in DEVICE_QUERIES and not ctx.is_gate_module:
            if not self.func_stack:
                self._emit(
                    "R2", node,
                    f"{q}() at import time eagerly initializes backends "
                    "(the test_capi 600 s hang class); defer it into a "
                    "function and route through kaminpar_tpu.utils.platform",
                )
            else:
                self._emit(
                    "R2", node,
                    f"direct {q}() bypasses the JAX_PLATFORMS-respecting "
                    "gate; use kaminpar_tpu.utils.platform instead",
                )

        # R3: int32-accumulating reductions on the 64-bit policy path
        if ctx.r3_applies:
            name = _terminal_name(node.func)
            if name in ACC_CALLS:
                for kw in node.keywords:
                    if kw.arg == "dtype" and _is_int32(kw.value, ctx):
                        self._emit(
                            "R3", node,
                            f"{name}(dtype=int32) can overflow at 64-bit "
                            "scale (edge counts / prefix sums / cut "
                            "accumulators); use dtypes.ACC_DTYPE",
                        )
            if (
                name == "astype"
                and node.args
                and _is_int32(node.args[0], ctx)
                and isinstance(node.func, ast.Attribute)
            ):
                for sub in ast.walk(node.func.value):
                    if (
                        isinstance(sub, ast.Call)
                        and _terminal_name(sub.func) in ACC_CALLS
                    ):
                        self._emit(
                            "R3", node,
                            "narrowing a reduction result to int32 "
                            "discards the 64-bit accumulator policy; "
                            "use dtypes.ACC_DTYPE",
                        )
                        break

        # R4: jit wrapper constructed per iteration / per evaluation
        if _is_jit_decorator(node, ctx):
            if self.loop_depth:
                self._emit(
                    "R4", node,
                    "jit wrapper constructed inside a loop compiles per "
                    "iteration; hoist it (jit caches by function identity)",
                )
            elif (
                node.args
                and isinstance(node.args[0], ast.Lambda)
                and self.func_stack
            ):
                self._emit(
                    "R4", node,
                    "jax.jit of a fresh lambda retraces on every call of "
                    "the enclosing function; define the jitted function "
                    "at module level",
                )

        # R6: eager device-memory/cost introspection outside the gated
        # perf-barrier helpers
        if not ctx.is_perf_gate_module:
            if q in R6_QUERIES:
                self._emit(
                    "R6", node,
                    f"direct {q}() walks device state eagerly (R2's "
                    "hazard class); route through the gated perf "
                    "helpers (telemetry.perf.sample_memory / "
                    "utils.heap_profiler)",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in R6_METHODS
            ):
                self._emit(
                    "R6", node,
                    f".{node.func.attr}() introspects a compiled "
                    "executable/device eagerly; the perf observatory "
                    "(telemetry/perf.py) captures this at the compile "
                    "boundary — use its snapshot instead",
                )

        # call-graph pass (one-level inlining): a factored helper is no
        # longer assumed clean — the hazard fires AT THE CALL SITE,
        # where the staging fix belongs
        resolved = ctx.resolve_call(
            node, self.class_stack[-1] if self.class_stack else None
        )
        if resolved is not None and resolved.node not in self.func_stack:
            summary = ctx.helper_summary(resolved)
            # R1d: a call inside a span scope to a helper whose body
            # host-syncs distorts the span exactly like the inline pull
            # (the "factored into a helper" idiom, now verified).  Only
            # SAME-MODULE helpers are inlined here: a cross-module call
            # from a phase span lands on one of the package's
            # host-boundary APIs (host_graph_from_device, the host
            # refiners, quality notes), whose hostness is the hybrid
            # architecture's contract, not a hidden refactor artifact —
            # the documented blind spot (docs/static_analysis.md).
            if (
                self.span_depth > 0
                and summary.host_syncs
                and resolved.module is ctx.module_info
            ):
                hline, hdesc = summary.host_syncs[0]
                self._emit(
                    "R1", node,
                    f"call to '{resolved.qualname}' inside a telemetry "
                    f"span scope reaches a host sync ({hdesc} at "
                    f"{resolved.module.path}:{hline}); stage the pull "
                    "outside the span",
                )
            if not self.func_stack:
                # R2b/R6b: import-time reach — the helper may live in a
                # gate module (platform/perf), where the def site is
                # exempt, but CALLING it at import time still eagerly
                # initializes the backend (the test_capi hang class)
                if summary.device_queries:
                    qline, qdesc = summary.device_queries[0]
                    self._emit(
                        "R2", node,
                        f"import-time call to '{resolved.qualname}' "
                        f"reaches {qdesc} ({resolved.module.path}:"
                        f"{qline}); defer it into a function",
                    )
                if summary.perf_introspections:
                    pline, pdesc = summary.perf_introspections[0]
                    self._emit(
                        "R6", node,
                        f"import-time call to '{resolved.qualname}' "
                        f"reaches {pdesc} ({resolved.module.path}:"
                        f"{pline}); defer it behind the perf gate",
                    )

        # R5: gather plans must be checked against the slot cap
        if _terminal_name(node.func) == "build_gather_plan":
            encl = self.func_stack[-1] if self.func_stack else ctx.tree
            encl_name = getattr(encl, "name", "<module>")
            if encl_name != "build_gather_plan" and not _has_cap_check(encl):
                self._emit(
                    "R5", node,
                    "build_gather_plan() without a slot-cap check in the "
                    "enclosing scope: skewed graphs inflate num_slots to "
                    "a multiple of m (ADVICE r5 medium); compare "
                    "plan.num_slots / use plan_within_cap before keeping "
                    "the plan",
                )

        self.generic_visit(node)


def _has_cap_check(scope: ast.AST) -> bool:
    """A real cap check: plan_within_cap (or the builder's max_slots=
    abort) is used, or num_slots appears inside a COMPARISON — a bare
    num_slots mention (telemetry logging) is not a cap."""
    for sub in ast.walk(scope):
        if isinstance(sub, ast.Call):
            if _terminal_name(sub.func) == "plan_within_cap":
                return True
            if any(kw.arg == "max_slots" for kw in sub.keywords):
                return True
        if isinstance(sub, ast.Compare):
            for part in ast.walk(sub):
                if isinstance(part, ast.Attribute) and part.attr == "num_slots":
                    return True
    return False


def run_rules(ctx: ModuleContext) -> List[Finding]:
    walker = _RuleWalker(ctx)
    walker.visit(ctx.tree)
    return walker.findings
