"""tpulint — codebase-specific AST static analysis for the JAX pipeline.

The round-5 verdict and advisor findings were all *statically visible*
in the Python source before they cost a round: the C-ABI driver eagerly
initialized a TPU backend despite ``JAX_PLATFORMS=cpu`` and hung the
suite 600 s; trace-time comm accounting silently under/over-counted;
int32 tags and accumulators capped scale; routed-gather plans could
inflate without bound on skewed graphs.  tpulint encodes each incident
class as a rule so future perf PRs cannot silently reintroduce them:

  R1  host-sync primitives (``.item()``, ``int()/float()/bool()`` of jax
      values, ``np.asarray`` of device values, Python ``if`` on traced
      expressions) inside functions reachable from ``jax.jit``-decorated
      code or inside telemetry span scopes;
  R2  eager/ungated device or backend queries — ``jax.devices()`` et al.
      must go through ``kaminpar_tpu.utils.platform`` (the lazy,
      ``JAX_PLATFORMS``-respecting gate), and must never run at import
      time;
  R3  32-bit accumulation (``dtype=...int32`` on cumsum/sum/segment_sum
      class reductions, int32 astype of reduction results) in ``ops/``,
      ``graphs/``, ``parallel/`` — the ``dtypes.py`` 64-bit policy owns
      accumulator widths;
  R4  retrace hygiene — jit wrappers constructed inside loops or around
      fresh lambdas retrace/recompile per evaluation;
  R5  routed-gather plan builders must check the plan against a slot cap
      (``plan_within_cap`` / ``num_slots``) before keeping it;
  R6  eager device-memory/cost introspection must stay behind the gated
      perf helpers (``telemetry.perf`` / ``utils.heap_profiler``);
  R7  SPMD collective symmetry — rank-dependent control flow
      (``agreement.rank()``, ``jax.process_index()``, ``*RANK*`` env
      reads) must not guard a collective: ranks that skip a ``psum``
      deadlock the ranks that entered it;
  R8  exception hygiene — broad ``except Exception`` around the
      degradation/fault surface must route through
      ``policy.with_fallback``/``classify`` or re-raise, never swallow;
  R9  schema-pin consistency (cross-file) — the run-report
      ``SCHEMA_VERSION``, the schema enum, the checker conditional and
      the highest transition fixture must agree.

Since PR 17 the engine carries an intra-package call graph: span-scope
and rank-guard analysis follows factored helpers ONE call deep, so a
host pull hidden inside a small helper invoked under ``Timer.scope``
still fires (docs/static_analysis.md#call-graph has the semantics and
the blind spots).

Usage:  ``python -m kaminpar_tpu.lint [paths...]`` — see ``--help`` and
docs/static_analysis.md.  Findings are suppressible per line with
``# tpulint: disable=R1[,R2...]`` (or per file with ``disable-file=``)
and ratcheted via the checked-in baseline
``scripts/tpulint_baseline.json`` (empty since PR 17; the CLI refuses
``--write-baseline`` runs that would grow it).
"""

from __future__ import annotations

from .engine import (  # noqa: F401
    Finding,
    LintConfig,
    RULES,
    lint_file,
    lint_paths,
    lint_source,
)
from .baseline import (  # noqa: F401
    diff_against_baseline,
    load_baseline,
    write_baseline,
)
