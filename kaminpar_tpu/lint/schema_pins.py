"""tpulint R9: run-report schema-version pin consistency (cross-file).

The run-report schema version is pinned in FOUR places that have been
hand-synced v7 -> v12 across six PRs, each bump a chance for silent
drift:

  1. the producer: ``SCHEMA_VERSION = N`` in
     ``kaminpar_tpu/telemetry/report.py`` — what live runs emit;
  2. the schema: the ``schema_version`` enum in
     ``kaminpar_tpu/telemetry/run_report.schema.json`` — its max must
     be N or the producer's own output fails validation;
  3. the checker: the selftest conditional in
     ``scripts/check_report_schema.py`` (``schema_version != N``) —
     stale, and the gate accepts an old producer;
  4. the transition fixtures: the highest ``_minimal_vK_report`` in the
     same script must be K = N-1 — every historical layout up to the
     previous version must still validate, and a missing fixture means
     the new transition is never covered.

Unlike R1-R8 this is not a per-file AST rule: it parses all four sites
in one pass and emits an R9 finding AT EACH SITE that disagrees with
the producer pin (so a single-site bump points at the site to fix).
All sites agreeing — including fixtures at exactly N-1 — is the only
clean state.

The pin locations are configurable (``LintConfig.r9_*``) so the fixture
pairs under ``tests/lint_fixtures/r9_{bad,good}/`` exercise the checker
against miniature site quads without touching the real ones.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import List, Optional, Tuple

from .engine import Finding, LintConfig, _repo_relative

_FIXTURE_RE = re.compile(r"^_minimal_v(\d+)_report$")


def _default_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _finding(path: str, line: int, message: str,
             code: str = "") -> Finding:
    return Finding(
        path=_repo_relative(path), rule="R9", line=line, col=0,
        symbol="<schema-pins>", message=message, code=code,
    )


def _read(path: str) -> Optional[str]:
    try:
        with open(path, encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return None


def _producer_pin(source: str) -> Optional[Tuple[int, int]]:
    """(value, line) of ``SCHEMA_VERSION = <int>``."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Name)
                    and tgt.id == "SCHEMA_VERSION"
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)
                ):
                    return int(node.value.value), node.lineno
    return None


def _schema_enum_max(source: str) -> Optional[int]:
    try:
        schema = json.loads(source)
    except json.JSONDecodeError:
        return None
    enum = (
        schema.get("properties", {})
        .get("schema_version", {})
        .get("enum")
    )
    if not isinstance(enum, list) or not enum:
        return None
    vals = [v for v in enum if isinstance(v, int)]
    return max(vals) if vals else None


def _checker_pins(source: str) -> Tuple[Optional[Tuple[int, int]],
                                        Optional[Tuple[int, int]]]:
    """((conditional value, line), (max fixture K, line)) from the
    check script: the ``.get("schema_version") != N`` selftest
    conditional (max when several) and the highest
    ``_minimal_vK_report`` def."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None, None
    cond: Optional[Tuple[int, int]] = None
    fixture: Optional[Tuple[int, int]] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare) and len(node.ops) == 1 and (
            isinstance(node.ops[0], ast.NotEq)
        ):
            left, right = node.left, node.comparators[0]
            if not (isinstance(right, ast.Constant)
                    and isinstance(right.value, int)):
                continue
            is_version_read = (
                isinstance(left, ast.Call)
                and isinstance(left.func, ast.Attribute)
                and left.func.attr == "get"
                and left.args
                and isinstance(left.args[0], ast.Constant)
                and left.args[0].value == "schema_version"
            ) or (
                isinstance(left, ast.Subscript)
                and isinstance(left.slice, ast.Constant)
                and left.slice.value == "schema_version"
            )
            if is_version_read and (
                cond is None or right.value > cond[0]
            ):
                cond = (int(right.value), node.lineno)
        elif isinstance(node, ast.FunctionDef):
            m = _FIXTURE_RE.match(node.name)
            if m:
                k = int(m.group(1))
                if fixture is None or k > fixture[0]:
                    fixture = (k, node.lineno)
    return cond, fixture


def check_schema_pins(config: Optional[LintConfig] = None) -> List[Finding]:
    config = config or LintConfig()
    root = config.r9_root or _default_root()
    producer_path = os.path.join(root, config.r9_producer_rel)
    schema_path = os.path.join(root, config.r9_schema_rel)
    checker_path = os.path.join(root, config.r9_checker_rel)

    findings: List[Finding] = []

    producer_src = _read(producer_path)
    schema_src = _read(schema_path)
    checker_src = _read(checker_path)
    if producer_src is None or schema_src is None or checker_src is None:
        # a repo without the report stack (path-subset runs, foreign
        # trees) has no pins to keep consistent — R9 is vacuous there
        return findings

    producer = _producer_pin(producer_src)
    enum_max = _schema_enum_max(schema_src)
    cond, fixture = _checker_pins(checker_src)

    if producer is None:
        findings.append(_finding(
            producer_path, 0,
            "no `SCHEMA_VERSION = <int>` pin found in the report "
            "producer — R9 cannot verify the schema quad",
        ))
        return findings
    pin, pin_line = producer
    quad = (
        f"producer={pin}, schema enum max={enum_max}, "
        f"checker conditional={cond[0] if cond else None}, "
        f"highest fixture=v{fixture[0] if fixture else None}"
    )

    if enum_max is None:
        findings.append(_finding(
            schema_path, 0,
            "schema_version enum missing/empty in run_report.schema.json",
        ))
    elif enum_max != pin:
        findings.append(_finding(
            schema_path, 0,
            f"schema enum tops out at {enum_max} but the producer emits "
            f"{pin} ({quad}); every pin site must be bumped together",
        ))

    if cond is None:
        findings.append(_finding(
            checker_path, 0,
            "no `schema_version != <int>` selftest conditional found in "
            "the schema checker",
        ))
    elif cond[0] != pin:
        findings.append(_finding(
            checker_path, cond[1],
            f"selftest conditional pins {cond[0]} but the producer emits "
            f"{pin} ({quad}); every pin site must be bumped together",
        ))

    if fixture is None:
        findings.append(_finding(
            checker_path, 0,
            "no `_minimal_v*_report` transition fixture found in the "
            "schema checker",
        ))
    elif fixture[0] != pin - 1:
        findings.append(_finding(
            checker_path, fixture[1],
            f"highest transition fixture is _minimal_v{fixture[0]}_report "
            f"but the producer emits {pin} — expected v{pin - 1} "
            f"({quad}); add the fixture for the PREVIOUS version when "
            "bumping",
        ))

    # the producer itself is only "wrong" relative to the majority: when
    # all three other sites agree with each other but not with it, point
    # at the producer line
    others = [
        v for v in (
            enum_max,
            cond[0] if cond else None,
            (fixture[0] + 1) if fixture else None,
        ) if v is not None
    ]
    if others and all(v == others[0] for v in others) and others[0] != pin:
        findings.append(_finding(
            producer_path, pin_line,
            f"SCHEMA_VERSION = {pin} disagrees with the other three pin "
            f"sites, which all say {others[0]} ({quad})",
        ))
    return findings
