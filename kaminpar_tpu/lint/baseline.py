"""tpulint baseline: land clean, ratchet down.

The baseline is a checked-in JSON multiset of accepted findings
(``scripts/tpulint_baseline.json``).  A lint run fails only on findings
NOT in the baseline, so the tool gates new hazards from day one while
the accepted backlog is burned down; stale entries (baselined findings
that no longer fire) are reported so the file can be regenerated
smaller — the ratchet direction is enforced socially (never regenerate
to a bigger file; docs/static_analysis.md#baseline-ratchet).

Entries match on ``(path, rule, code)`` where ``code`` is the stripped
source line — stable under unrelated edits that shift line numbers, the
failure mode that makes line-keyed baselines rot instantly.  Line
numbers are stored for human readers only.
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass, field
from typing import List, Tuple

from .engine import Finding

BASELINE_VERSION = 1


def _key(path: str, rule: str, code: str) -> Tuple[str, str, str]:
    return (path, rule, code)


@dataclass
class BaselineDiff:
    new: List[Finding] = field(default_factory=list)  # fail the run
    accepted: List[Finding] = field(default_factory=list)  # in baseline
    stale: List[dict] = field(default_factory=list)  # baselined, gone


def load_baseline(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} "
            f"(expected {BASELINE_VERSION})"
        )
    return list(data.get("entries", []))


def write_baseline(path: str, findings: List[Finding]) -> None:
    entries = [
        {
            "path": f.path,
            "rule": f.rule,
            "line": f.line,
            "code": f.code,
            "message": f.message,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {
                "version": BASELINE_VERSION,
                "tool": "tpulint",
                "note": (
                    "accepted findings; regenerate ONLY to shrink "
                    "(python -m kaminpar_tpu.lint --write-baseline)"
                ),
                "entries": entries,
            },
            fh,
            indent=2,
        )
        fh.write("\n")


def diff_against_baseline(findings: List[Finding],
                          entries: List[dict]) -> BaselineDiff:
    budget = Counter(
        _key(e["path"], e["rule"], e.get("code", "")) for e in entries
    )
    diff = BaselineDiff()
    for f in findings:
        k = _key(f.path, f.rule, f.code)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            diff.accepted.append(f)
        else:
            diff.new.append(f)
    for e in entries:
        k = _key(e["path"], e["rule"], e.get("code", ""))
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            diff.stale.append(e)
    return diff
