"""tpulint SPMD rules: R7 collective symmetry, R8 exception hygiene.

R7 is the static half of the PR-12 divergence sentinel.  The dynamic
sentinel catches a fleet whose ranks disagree *after* the fact; R7 flags
the code shape that causes it before it ships: control flow that
branches on a rank-dependent value (``agreement.rank()``,
``jax.process_index()``, ``is_primary_process()``, ``*RANK*`` env
reads) and reaches an SPMD collective inside the guarded branch —
lexically or one helper call deep via the package call graph.  On an
8-chip mesh the rank that skips a ``psum`` does not fail loudly; the
seven that entered it hang until the watchdog fires (Tera-Scale
composition, PAPERS.md arXiv 2410.19119).  The deliberate single-writer
idiom (every rank agrees on the data, rank 0 alone writes the
checkpoint/report) stays allowlisted via
``LintConfig.r7_allow_suffixes`` — those branches do host I/O, not
collectives.

R8 is the documented "candidate rule" from docs/static_analysis.md,
promoted.  The degradation contract (resilience/policy.py) requires
every optional-fast-path failure to be *classified*: structured
``DegradationError``s degrade visibly, anything else propagates because
an unclassified exception is a bug.  A bare/broad ``except Exception``
wrapped around the fault surface (``with_fallback``, ``maybe_inject``,
any ``site=`` call) defeats exactly that — it swallows both the
degradation and real bugs, and hides the failure from the chaos suite.
A broad handler is fine when it ROUTES: re-raises, raises a structured
error, or calls ``classify``.  Boundary layers whose contract is
"never let any exception cross" (serving isolation, supervisor marshal,
telemetry best-effort) are allowlisted via
``LintConfig.r8_boundary_parts``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .callgraph import (
    COLLECTIVE_CALLS,
    FAULT_SURFACE_CALLS,
    RANK_SOURCE_CALLS,
    RANK_SOURCE_QUALNAMES,
    _is_env_rank_read,
    terminal_name,
)
from .engine import Finding, ModuleContext

_BROAD_EXC_NAMES = frozenset({"Exception", "BaseException"})

#: handler-body calls that count as routing the exception into the
#: degradation contract rather than swallowing it
_ROUTING_CALLS = frozenset({"classify", "with_fallback"})


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    """``except:``, ``except Exception``, ``except BaseException`` or a
    tuple containing one of them."""
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for item in types:
        name = terminal_name(item)
        if name in _BROAD_EXC_NAMES:
            return True
    return False


def _handler_routes(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises (bare or structured) or calls
    into the classification machinery — the contract's escape hatches."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and (
            terminal_name(node.func) in _ROUTING_CALLS
        ):
            return True
    return False


def _own_statements(body):
    """Walk statements pruning nested function bodies (a closure's
    hazards belong to its own call sites)."""
    work = list(body)
    while work:
        node = work.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        work.extend(ast.iter_child_nodes(node))


class _SpmdWalker(ast.NodeVisitor):
    def __init__(self, ctx: ModuleContext) -> None:
        self.ctx = ctx
        self.findings: List[Finding] = []
        self.func_stack: List[ast.AST] = []
        self.class_stack: List[str] = []
        self.rank_guard_depth = 0
        path = ctx.path.replace("\\", "/")
        self.r7_allowed = any(
            path.endswith(sfx) for sfx in ctx.config.r7_allow_suffixes
        )
        self.r8_allowed = any(
            part in path for part in ctx.config.r8_boundary_parts
        )

    # -- shared helpers ----------------------------------------------------

    def _symbol(self) -> str:
        if self.func_stack:
            return ".".join(
                f.name for f in self.func_stack
                if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
            )
        return "<module>"

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        self.findings.append(
            Finding(
                path=self.ctx.path,
                rule=rule,
                line=line,
                col=getattr(node, "col_offset", 0),
                symbol=self._symbol(),
                message=message,
                code=self.ctx.line_text(line),
            )
        )

    def _resolve(self, call: ast.Call):
        return self.ctx.resolve_call(
            call, self.class_stack[-1] if self.class_stack else None
        )

    # -- structure ---------------------------------------------------------

    def _visit_function(self, node) -> None:
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    # -- R7: rank-dependent guards around collectives ----------------------

    def _is_rank_dependent(self, test: ast.AST) -> bool:
        ctx = self.ctx
        for sub in ast.walk(test):
            if not isinstance(sub, ast.Call):
                continue
            name = terminal_name(sub.func)
            if name in RANK_SOURCE_CALLS:
                return True
            if ctx.qualname(sub.func) in RANK_SOURCE_QUALNAMES:
                return True
            if _is_env_rank_read(sub, ctx.aliases):
                return True
            resolved = self._resolve(sub)
            if resolved is not None and ctx.helper_summary(
                resolved
            ).rank_dependent:
                return True
        return False

    def _visit_guarded(self, node) -> None:
        rank_dep = self._is_rank_dependent(node.test)
        self.visit(node.test)
        self.rank_guard_depth += 1 if rank_dep else 0
        # BOTH branches of a rank-dependent if are asymmetric: whichever
        # side carries the collective, some ranks take the other one
        for stmt in list(node.body) + list(getattr(node, "orelse", [])):
            self.visit(stmt)
        self.rank_guard_depth -= 1 if rank_dep else 0

    visit_If = _visit_guarded
    visit_While = _visit_guarded

    # -- R8: broad handlers around the fault surface -----------------------

    def _fault_surface_reach(self, body) -> Optional[str]:
        """Description of the first degradation/fault-surface call the
        try body reaches (lexically or one helper call deep), or None."""
        for node in _own_statements(body):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name in FAULT_SURFACE_CALLS:
                return f"{name}()"
            if any(kw.arg == "site" for kw in node.keywords):
                return f"{name or '<call>'}(site=...)"
            resolved = self._resolve(node)
            if resolved is not None:
                summary = self.ctx.helper_summary(resolved)
                if summary.fault_surface:
                    fline, fdesc = summary.fault_surface[0]
                    return (
                        f"{fdesc} via '{resolved.qualname}' "
                        f"({resolved.module.path}:{fline})"
                    )
        return None

    def visit_Try(self, node: ast.Try) -> None:
        if not self.r8_allowed:
            reach = None
            for handler in node.handlers:
                if not _is_broad_handler(handler):
                    continue
                if _handler_routes(handler):
                    continue
                if reach is None:
                    reach = self._fault_surface_reach(node.body)
                if reach is None:
                    break  # try body never touches the fault surface
                self._emit(
                    "R8", handler,
                    f"broad except swallows failures of the degradation "
                    f"contract (try body reaches {reach}); raise a "
                    "structured error, call classify(), or let it "
                    "propagate — with_fallback owns the catch",
                )
        self.generic_visit(node)

    # -- calls -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if self.rank_guard_depth > 0 and not self.r7_allowed:
            name = terminal_name(node.func)
            if name in COLLECTIVE_CALLS:
                self._emit(
                    "R7", node,
                    f"collective {name}() under rank-dependent control "
                    "flow: ranks that skip it deadlock the ranks that "
                    "entered it — hoist the collective out of the guard "
                    "(every rank must reach it)",
                )
            else:
                resolved = self._resolve(node)
                if resolved is not None and resolved.node not in (
                    self.func_stack
                ):
                    summary = self.ctx.helper_summary(resolved)
                    if summary.collectives:
                        cline, cdesc = summary.collectives[0]
                        self._emit(
                            "R7", node,
                            f"call to '{resolved.qualname}' under "
                            f"rank-dependent control flow reaches "
                            f"collective {cdesc} "
                            f"({resolved.module.path}:{cline}); every "
                            "rank must reach it",
                        )
        self.generic_visit(node)


def run_spmd_rules(ctx: ModuleContext) -> List[Finding]:
    walker = _SpmdWalker(ctx)
    walker.visit(ctx.tree)
    return walker.findings
