"""tpulint CLI: ``python -m kaminpar_tpu.lint [paths...]``.

Exit codes: 0 clean (vs baseline), 1 new findings, 2 usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .baseline import diff_against_baseline, load_baseline, write_baseline
from .engine import RULES, LintConfig, lint_paths

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "scripts", "tpulint_baseline.json")

_SARIF_VERSION = "2.1.0"
_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _sarif_report(new, total: int, baseline_entries: int) -> dict:
    """Minimal SARIF 2.1.0 run: one tool with the rule table, one result
    per NEW finding (baselined findings are suppressed by definition)."""
    return {
        "$schema": _SARIF_SCHEMA,
        "version": _SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpulint",
                        "informationUri": "docs/static_analysis.md",
                        "rules": [
                            {
                                "id": rid,
                                "shortDescription": {"text": desc},
                            }
                            for rid, desc in RULES.items()
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "error",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {
                                        "startLine": max(f.line, 1),
                                        "startColumn": f.col + 1,
                                    },
                                }
                            }
                        ],
                    }
                    for f in new
                ],
                "properties": {
                    "totalFindings": total,
                    "baselineEntries": baseline_entries,
                },
            }
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kaminpar_tpu.lint",
        description=(
            "tpulint: AST hot-path hazard checker for the kaminpar-tpu "
            "JAX pipeline (rules R1-R9; see docs/static_analysis.md)"
        ),
    )
    ap.add_argument(
        "paths", nargs="*", default=None,
        help="files or directories to lint (default: the kaminpar_tpu "
        "package)",
    )
    ap.add_argument(
        "--baseline", default=None, metavar="PATH",
        help=f"baseline JSON of accepted findings (default: "
        f"{os.path.relpath(DEFAULT_BASELINE, _REPO_ROOT)} when present)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding (ignore the baseline)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="accept the current findings as the new baseline (use only "
        "to SHRINK the file — the ratchet policy refuses growth)",
    )
    ap.add_argument(
        "--select", "--rules", dest="select", default=None, metavar="RULES",
        help="comma-separated rule subset, e.g. R2,R3",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in RULES.items():
            print(f"{rid}  {desc}")
        return 0

    paths = args.paths or [os.path.join(_REPO_ROOT, "kaminpar_tpu")]
    for p in paths:
        if not os.path.exists(p):
            print(f"tpulint: no such path: {p}", file=sys.stderr)
            return 2

    config = LintConfig()
    if args.select:
        wanted = tuple(
            r.strip().upper() for r in args.select.split(",") if r.strip()
        )
        unknown = [r for r in wanted if r not in RULES]
        if unknown:
            print(f"tpulint: unknown rule(s): {unknown}", file=sys.stderr)
            return 2
        config.rules = wanted

    findings = lint_paths(paths, config)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None
    )

    if args.write_baseline:
        # a rule or path subset would silently TRUNCATE the baseline to
        # that subset's findings, breaking every full run afterwards
        if args.select:
            print(
                "tpulint: refusing --write-baseline with --select "
                "(a rule subset would truncate the baseline)",
                file=sys.stderr,
            )
            return 2
        default_pkg = os.path.abspath(os.path.join(_REPO_ROOT, "kaminpar_tpu"))
        norm = sorted(os.path.abspath(p).rstrip(os.sep) for p in paths)
        if args.baseline is None and norm != [default_pkg]:
            print(
                "tpulint: refusing to overwrite the default baseline "
                "from a path subset; pass --baseline PATH explicitly",
                file=sys.stderr,
            )
            return 2
        out = args.baseline or DEFAULT_BASELINE
        # the ratchet only turns one way: a baseline rewrite may shrink
        # or re-key the accepted set, never grow it.  New findings must
        # be FIXED (or suppressed with an inline justification), not
        # absorbed into the baseline.
        if os.path.exists(out):
            try:
                existing = load_baseline(out)
            except (OSError, ValueError, json.JSONDecodeError):
                existing = None
            if existing is not None and len(findings) > len(existing):
                print(
                    f"tpulint: refusing --write-baseline: {len(findings)} "
                    f"findings would GROW the baseline from "
                    f"{len(existing)} entries (the ratchet only shrinks); "
                    "fix the new findings or suppress them inline with a "
                    "justification",
                    file=sys.stderr,
                )
                return 2
        write_baseline(out, findings)
        print(f"tpulint: wrote {len(findings)} entries to {out}")
        return 0

    baseline_entries = 0
    if args.no_baseline or baseline_path is None:
        new, stale = findings, []
    else:
        try:
            entries = load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"tpulint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        baseline_entries = len(entries)
        diff = diff_against_baseline(findings, entries)
        new, stale = diff.new, diff.stale

    if args.format == "json":
        print(
            json.dumps(
                {
                    "new": [f.to_dict() for f in new],
                    "total": len(findings),
                    "baseline_entries": baseline_entries,
                    "stale_baseline_entries": len(stale),
                },
                indent=2,
            )
        )
    elif args.format == "sarif":
        print(json.dumps(
            _sarif_report(new, len(findings), baseline_entries), indent=2
        ))
    else:
        for f in new:
            print(f.render())
        suffix = "" if args.no_baseline or baseline_path is None else (
            f" ({len(findings) - len(new)} baselined)"
        )
        print(
            f"tpulint: {len(new)} new finding(s), {len(findings)} "
            f"total{suffix}"
        )
        if stale:
            print(
                f"tpulint: ratchet: {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} no longer fire — "
                "shrink the baseline with --write-baseline"
            )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
