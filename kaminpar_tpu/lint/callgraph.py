"""tpulint call graph: intra-package def/import resolution and
one-level helper hazard summaries.

The v1 engine was deliberately module-local: span-scope analysis (R1)
only saw hazards written *lexically* inside a ``with scoped_timer``
block, so factoring a host pull into a helper silently passed the
check — the loophole every "hook shape" fixture leaned on.  This module
closes it one level deep:

  * :class:`PackageIndex` parses every linted file once and records, per
    module, its top-level functions, its class methods, and an import
    map that resolves *relative* imports (``from ..telemetry import
    quality``) against the module's dotted name — the package's actual
    import idiom, which the v1 alias map skipped;
  * :func:`PackageIndex.resolve` maps a call expression (``helper(..)``,
    ``mod.helper(..)``, ``self.method(..)``) to the function definition
    it names, same-module or cross-module;
  * :func:`PackageIndex.summary` extracts a :class:`HelperSummary` of
    the hazards written directly in that function's body — host-sync
    primitives, device/backend queries, perf introspections, SPMD
    collectives, fault-surface entries, rank reads.

Rules consult the summary at the call site: a call inside a span scope
to a helper whose body host-syncs is the same distortion as the inline
pull, and is reported at the call site (where the fix belongs).

Known blind spots, by design (documented in docs/static_analysis.md):
inlining is ONE level (a pull two calls deep stays invisible — the
baseline ratchet's job, not the linter's); resolution is name-based
(no dataflow: a helper passed as a callback is not followed); and
suppression comments in the *helper's* file are honored, so a helper
whose hazard line carries a justified ``# tpulint: disable=`` never
taints its callers.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# shared hazard surfaces (rules.py / spmd.py import these; this module
# is the bottom layer and imports nothing from the rest of the linter)

#: R2: the device/backend discovery surface that must stay behind the
#: utils.platform gate (eager discovery is what initialized the axon
#: tunnel despite JAX_PLATFORMS=cpu and hung test_capi 600 s).
DEVICE_QUERIES = frozenset(
    {
        "jax.devices",
        "jax.local_devices",
        "jax.device_count",
        "jax.local_device_count",
        "jax.default_backend",
        "jax.process_index",
        "jax.process_count",
        "jax.lib.xla_bridge.get_backend",
        "jax.extend.backend.get_backend",
    }
)

#: R6: eager memory/cost introspection (see rules.py for the rule text).
R6_QUERIES = frozenset(
    {
        "jax.live_arrays",
        "jax.profiler.device_memory_profile",
    }
)
R6_METHODS = frozenset(
    {
        "cost_analysis",
        "memory_analysis",
        "get_compiled_memory_stats",
        "device_memory_profile",
    }
)

#: R7: calls every rank of an SPMD fleet must reach together — a rank
#: that skips one deadlocks the survivors inside the collective (the
#: static half of the PR-12 divergence sentinel).  Terminal names, so
#: `lax.psum`, `mesh.halo_exchange` and bare `psum` all match.
COLLECTIVE_CALLS = frozenset(
    {
        "psum",
        "psum_scatter",
        "pmean",
        "pmax",
        "pmin",
        "all_gather",
        "allgather",
        "all_to_all",
        "ppermute",
        "pshuffle",
        "shard_map",
        "shard_map_compat",
        "agree_max",
        "agree_min",
        "agree_sum",
        "gather_i64",
        "process_allgather",
        "halo_exchange",
        "sync_global_devices",
        "broadcast_one_to_all",
    }
)

#: R7: expressions whose value differs per rank — control flow branching
#: on one of these in front of a collective is the divergence hazard.
RANK_SOURCE_CALLS = frozenset(
    {
        "rank",
        "process_index",
        "local_rank",
        "is_primary_process",
        "is_primary",
    }
)
RANK_SOURCE_QUALNAMES = frozenset(
    {
        "jax.process_index",
    }
)
_RANK_ENV_RE = re.compile(r"RANK", re.IGNORECASE)

#: R8: entry points of the degradation/fault contract
#: (resilience/policy.py, resilience/faults.py).  A broad handler
#: swallowing exceptions around one of these defeats the classification
#: the contract exists to enforce.
FAULT_SURFACE_CALLS = frozenset(
    {
        "with_fallback",
        "maybe_inject",
    }
)

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9]+(?:\s*,\s*[A-Za-z0-9]+)*)"
)


def collect_aliases(tree: ast.Module) -> Dict[str, str]:
    """Absolute-import alias map (``jnp`` -> ``jax.numpy``); the same
    map the v1 engine built, shared here so summaries resolve qualnames
    identically to the lexical rules."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def qualname_in(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Dotted name of a Name/Attribute chain with aliases resolved."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def terminal_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def module_name_for(path: str) -> str:
    """Dotted module name for a repo-relative posix path; files outside
    a package tree (fixtures, snippets) get their bare stem."""
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[: -len(".py")]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    parts = p.split("/")
    if "kaminpar_tpu" in parts:
        parts = parts[parts.index("kaminpar_tpu"):]
        return ".".join(parts)
    return parts[-1]


@dataclass
class HelperSummary:
    """Hazards written directly in one function's body (nested defs
    excluded: closures run at their own call sites, not this one)."""

    host_syncs: List[Tuple[int, str]] = field(default_factory=list)
    device_queries: List[Tuple[int, str]] = field(default_factory=list)
    perf_introspections: List[Tuple[int, str]] = field(default_factory=list)
    collectives: List[Tuple[int, str]] = field(default_factory=list)
    fault_surface: List[Tuple[int, str]] = field(default_factory=list)
    rank_dependent: bool = False


@dataclass
class FunctionInfo:
    name: str
    qualname: str  # module.func or module.Class.func
    node: ast.AST
    module: "ModuleInfo"


class ModuleInfo:
    """One parsed module as the call graph sees it."""

    def __init__(self, path: str, source: str, tree: ast.Module) -> None:
        self.path = path
        self.name = module_name_for(path)
        self.tree = tree
        self.aliases = collect_aliases(tree)
        self.suppressed_lines = _suppressed_lines(source)
        # top-level defs and class methods (one level of class nesting —
        # the package's layout; deeper nesting is a blind spot)
        self.functions: Dict[str, FunctionInfo] = {}
        self.methods: Dict[str, Dict[str, FunctionInfo]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = FunctionInfo(
                    node.name, f"{self.name}.{node.name}", node, self
                )
            elif isinstance(node, ast.ClassDef):
                table: Dict[str, FunctionInfo] = {}
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        table[sub.name] = FunctionInfo(
                            sub.name,
                            f"{self.name}.{node.name}.{sub.name}",
                            sub, self,
                        )
                self.methods[node.name] = table
        # import map including RELATIVE imports resolved against this
        # module's dotted name: local name -> dotted target
        self.imports: Dict[str, str] = dict(self.aliases)
        pkg_parts = self.name.split(".")[:-1]
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.level:
                # `from ..x import y` with level=2 strips one extra part
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                mod = ".".join(base + (node.module or "").split("."))
                mod = mod.strip(".")
                for a in node.names:
                    self.imports[a.asname or a.name] = (
                        f"{mod}.{a.name}" if mod else a.name
                    )


def _suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """Per-line suppressed rule sets, with the comment-line-above
    convention (mirrors engine._parse_suppressions; file-wide
    suppressions are folded in by the caller via line 0)."""
    per_line: Dict[int, Set[str]] = {}
    lines = source.splitlines()
    for lineno, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        kind, rules = m.groups()
        names = {r.strip().upper() for r in rules.split(",") if r.strip()}
        if kind == "disable-file":
            per_line.setdefault(0, set()).update(names)
            continue
        target = lineno
        if line.lstrip().startswith("#"):
            nxt = lineno + 1
            while nxt <= len(lines) and lines[nxt - 1].lstrip().startswith("#"):
                nxt += 1
            target = nxt
        per_line.setdefault(target, set()).update(names)
    return per_line


def _mentions_jax(node: ast.AST, aliases: Dict[str, str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            q = qualname_in(sub, aliases)
            if q and (q == "jax" or q.startswith("jax.")):
                return True
    return False


def _own_body_nodes(fn: ast.AST):
    """Walk a function's own statements, pruning nested function/lambda
    bodies (those hazards belong to the closure's call sites)."""
    work = list(getattr(fn, "body", []))
    while work:
        node = work.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        work.extend(ast.iter_child_nodes(node))


def _is_env_rank_read(node: ast.Call, aliases: Dict[str, str]) -> bool:
    q = qualname_in(node.func, aliases)
    if q not in ("os.environ.get", "os.getenv"):
        return False
    return any(
        isinstance(a, ast.Constant) and isinstance(a.value, str)
        and _RANK_ENV_RE.search(a.value)
        for a in node.args
    )


class PackageIndex:
    """Cross-module def/import resolution over one lint invocation."""

    def __init__(self) -> None:
        self.by_name: Dict[str, ModuleInfo] = {}
        self.by_path: Dict[str, ModuleInfo] = {}
        self._summaries: Dict[int, HelperSummary] = {}

    def add(self, path: str, source: str, tree: ast.Module) -> ModuleInfo:
        info = ModuleInfo(path, source, tree)
        self.by_name[info.name] = info
        self.by_path[path] = info
        return info

    # -- resolution --------------------------------------------------------

    def resolve(self, module: ModuleInfo, call: ast.Call,
                enclosing_class: Optional[str] = None
                ) -> Optional[FunctionInfo]:
        """The function definition a call names, or None.  Handles
        ``helper()``, ``imported_helper()``, ``mod.helper()`` and
        ``self.method()`` / ``cls.method()`` (within the lexically
        enclosing class)."""
        func = call.func
        if isinstance(func, ast.Name):
            local = module.functions.get(func.id)
            if local is not None:
                return local
            target = module.imports.get(func.id)
            if target:
                return self._lookup_dotted(target)
            return None
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and enclosing_class:
                    table = module.methods.get(enclosing_class, {})
                    return table.get(func.attr)
                target = module.imports.get(base.id)
                if target:
                    mod = self.by_name.get(target)
                    if mod is not None:
                        return mod.functions.get(func.attr)
                    return self._lookup_dotted(f"{target}.{func.attr}")
        return None

    def _lookup_dotted(self, dotted: str) -> Optional[FunctionInfo]:
        mod_name, _, fn_name = dotted.rpartition(".")
        if not mod_name:
            return None
        mod = self.by_name.get(mod_name)
        if mod is not None:
            return mod.functions.get(fn_name)
        return None

    # -- summaries ---------------------------------------------------------

    def summary(self, fn: FunctionInfo) -> HelperSummary:
        cached = self._summaries.get(id(fn.node))
        if cached is not None:
            return cached
        s = self._summarize(fn)
        self._summaries[id(fn.node)] = s
        return s

    def _summarize(self, fn: FunctionInfo) -> HelperSummary:
        mod = fn.module
        aliases = mod.aliases
        s = HelperSummary()
        file_wide = mod.suppressed_lines.get(0, set())
        # a suppression ON (or commented above) the `def` line declares
        # the helper as a HOST-BOUNDARY function for that rule: its
        # hazards are its contract, so nothing is summarized and every
        # call site stays clean at once — one justified declaration at
        # the def instead of one suppression per sync line
        def_wide = mod.suppressed_lines.get(
            getattr(fn.node, "lineno", 0), set()
        )

        def allowed(rule: str, line: int) -> bool:
            if "ALL" in file_wide or rule in file_wide:
                return False
            if "ALL" in def_wide or rule in def_wide:
                return False
            at = mod.suppressed_lines.get(line, set())
            return not ("ALL" in at or rule in at)

        for node in _own_body_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            line = getattr(node, "lineno", 0)
            q = qualname_in(node.func, aliases)
            name = terminal_name(node.func)

            # R1-class host syncs (mirrors rules.py R1a/b/c exactly)
            if allowed("R1", line):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    s.host_syncs.append((line, ".item()"))
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("int", "float", "bool")
                    and node.func.id not in aliases
                    and node.args
                    and _mentions_jax(node.args[0], aliases)
                ):
                    s.host_syncs.append(
                        (line, f"{node.func.id}() of a jax value")
                    )
                elif (
                    q in ("numpy.asarray", "numpy.array")
                    and node.args
                    and not isinstance(
                        node.args[0], (ast.List, ast.Tuple, ast.Constant)
                    )
                ):
                    s.host_syncs.append((line, f"{q}()"))

            if q in DEVICE_QUERIES and allowed("R2", line):
                s.device_queries.append((line, f"{q}()"))

            if allowed("R6", line):
                if q in R6_QUERIES:
                    s.perf_introspections.append((line, f"{q}()"))
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in R6_METHODS
                ):
                    s.perf_introspections.append(
                        (line, f".{node.func.attr}()")
                    )

            if name in COLLECTIVE_CALLS and allowed("R7", line):
                s.collectives.append((line, f"{name}()"))

            if allowed("R8", line):
                if name in FAULT_SURFACE_CALLS or any(
                    kw.arg == "site" for kw in node.keywords
                ):
                    s.fault_surface.append(
                        (line, f"{name or '<call>'}()")
                    )

            if (
                name in RANK_SOURCE_CALLS
                or q in RANK_SOURCE_QUALNAMES
                or _is_env_rank_read(node, aliases)
            ):
                s.rank_dependent = True
        return s
