"""tpulint core: AST analysis, suppressions, file walking.

One analyzer instance handles one module.  The rule logic lives in
``rules.py`` (R1-R6) and ``spmd.py`` (R7/R8); ``schema_pins.py`` owns
the cross-file R9 check and ``callgraph.py`` the package index.  This
module owns the shared machinery every rule needs:

  * import alias resolution (``jnp`` -> ``jax.numpy``) so rules match
    fully-qualified names regardless of local import style;
  * the module-local jit call graph (which functions are
    ``jax.jit``-decorated or transitively called from one) for R1;
  * the cross-module :class:`callgraph.PackageIndex` (one-level helper
    inlining) so span-scope analysis follows factored helpers;
  * lexical context stacks (function nesting, loop depth, span-scope
    ``with`` blocks) maintained during a single AST walk;
  * ``# tpulint: disable=``/``disable-file=`` suppression parsing.

Per-module analysis stays deterministic and dependency-free; the call
graph adds exactly one level of inlining (a pull two calls deep is a
documented blind spot, docs/static_analysis.md#call-graph).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import callgraph as cg

RULES: Dict[str, str] = {
    "R1": "host-sync primitive in jit-reachable code or a span scope "
          "(lexically or one helper call deep)",
    "R2": "eager/ungated device or backend query (use utils.platform)",
    "R3": "32-bit accumulation where the dtypes.py 64-bit policy applies",
    "R4": "jit wrapper constructed per iteration/evaluation (retrace)",
    "R5": "routed-gather plan built without a slot cap check",
    "R6": "eager device-memory/cost introspection outside the gated "
          "perf helpers (telemetry.perf / utils.heap_profiler)",
    "R7": "rank-dependent control flow guarding an SPMD collective "
          "(the static half of the divergence sentinel)",
    "R8": "broad except around the degradation/fault surface without "
          "routing through with_fallback/classify",
    "R9": "run-report schema-version pin skew across producer/schema/"
          "checker/fixtures (cross-file)",
}

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9]+(?:\s*,\s*[A-Za-z0-9]+)*)"
)


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative, posix separators
    rule: str
    line: int
    col: int
    symbol: str  # enclosing function ('<module>' at top level)
    message: str
    code: str  # stripped source line, the churn-stable baseline key

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{self.symbol}] {self.message}"
        )

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "rule": self.rule,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "message": self.message,
            "code": self.code,
        }


@dataclass
class LintConfig:
    """Knobs the CLI and tests tune; defaults match the package layout."""

    # files allowed to call jax device/backend queries directly (the gate)
    gate_suffixes: Tuple[str, ...] = ("utils/platform.py",)
    # files allowed to walk live arrays / cost-analyze executables /
    # profile device memory directly (R6's gate: the perf observatory
    # and the heap profiler own those probes behind enabled() checks)
    perf_gate_suffixes: Tuple[str, ...] = (
        "telemetry/perf.py",
        "utils/heap_profiler.py",
    )
    # R3 fires only under these directory names (plus lint fixtures)
    r3_dirs: Tuple[str, ...] = ("ops", "graphs", "parallel", "lint_fixtures")
    # R7: the deliberate rank-0-writes idiom — checkpointing and report
    # emission are DOCUMENTED single-writer surfaces (every rank agrees
    # on the data first, rank 0 alone touches the filesystem), and the
    # agreement layer itself implements the collectives it guards
    r7_allow_suffixes: Tuple[str, ...] = (
        "resilience/checkpoint.py",
        "resilience/agreement.py",
        "telemetry/report.py",
    )
    # R8: legitimate broad-except boundaries — processes/layers whose
    # CONTRACT is "never let any exception cross" (serving isolation
    # marshals verdicts, the supervisor marshals worker death, telemetry
    # is best-effort by design).  Substring match on the posix path.
    r8_boundary_parts: Tuple[str, ...] = (
        "serving/service.py",
        "resilience/supervisor.py",
        "telemetry/",
    )
    # R9: the four schema-version pin sites (relative to r9_root; None
    # root = the repo that holds this package)
    r9_root: Optional[str] = None
    r9_producer_rel: str = "kaminpar_tpu/telemetry/report.py"
    r9_schema_rel: str = "kaminpar_tpu/telemetry/run_report.schema.json"
    r9_checker_rel: str = "scripts/check_report_schema.py"
    # rules to run (all by default)
    rules: Tuple[str, ...] = tuple(RULES)


def _parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """(per-line rule sets, file-wide rule set); 'all' disables everything.

    A ``# tpulint: disable=`` on a comment-only line applies to the next
    code line (so long statements can carry their justification above)."""
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    lines = source.splitlines()
    for lineno, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        kind, rules = m.groups()
        names = {r.strip().upper() for r in rules.split(",") if r.strip()}
        if kind == "disable-file":
            per_file |= names
            continue
        target = lineno
        if line.lstrip().startswith("#"):
            # comment-only line: attach to the next code line
            nxt = lineno + 1
            while nxt <= len(lines) and lines[nxt - 1].lstrip().startswith("#"):
                nxt += 1
            target = nxt
        per_line.setdefault(target, set()).update(names)
    return per_line, per_file


class ModuleContext:
    """Everything rules need to know about one parsed module."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 config: LintConfig,
                 index: Optional[cg.PackageIndex] = None) -> None:
        self.path = path
        self.source_lines = source.splitlines()
        self.tree = tree
        self.config = config
        self.aliases = _collect_aliases(tree)
        self.jit_reachable = _jit_reachable_functions(tree, self)
        self.is_gate_module = any(
            path.endswith(sfx) for sfx in config.gate_suffixes
        )
        self.is_perf_gate_module = any(
            path.endswith(sfx) for sfx in config.perf_gate_suffixes
        )
        parts = set(path.replace("\\", "/").split("/"))
        self.r3_applies = bool(parts & set(config.r3_dirs))
        # cross-module call graph; a single-module index is built on the
        # fly so same-file helpers resolve even in snippet/fixture runs
        if index is None:
            index = cg.PackageIndex()
            index.add(path, source, tree)
        self.index = index
        self.module_info = index.by_path.get(path)

    def resolve_call(self, node: ast.Call,
                     enclosing_class: Optional[str] = None
                     ) -> Optional[cg.FunctionInfo]:
        """The package-defined function a call names (same or cross
        module, ``self.method`` within the enclosing class), else None."""
        if self.module_info is None:
            return None
        return self.index.resolve(self.module_info, node, enclosing_class)

    def helper_summary(self, fn: cg.FunctionInfo) -> cg.HelperSummary:
        return self.index.summary(fn)

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with aliases resolved;
        None for anything that is not a plain chain."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.source_lines):
            return self.source_lines[lineno - 1].strip()
        return ""


_collect_aliases = cg.collect_aliases


_JIT_WRAPPERS = ("jax.jit", "jax.pmap")


def _is_jit_decorator(dec: ast.AST, ctx: "ModuleContext") -> bool:
    """@jax.jit, @jit (from jax), @functools.partial(jax.jit, ...),
    @jax.jit(...) — anything that makes the function a trace root."""
    q = ctx.qualname(dec)
    if q in _JIT_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        fq = ctx.qualname(dec.func)
        if fq in _JIT_WRAPPERS:
            return True
        if fq in ("functools.partial", "partial") and dec.args:
            return ctx.qualname(dec.args[0]) in _JIT_WRAPPERS
    return False


def _jit_reachable_functions(tree: ast.Module, ctx: "ModuleContext"
                             ) -> Set[ast.AST]:
    """Function nodes that are jit roots or transitively called from one
    (module-local, by simple name).  Nested defs inherit reachability
    from their enclosing function."""
    funcs: List[ast.AST] = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    by_name: Dict[str, List[ast.AST]] = {}
    for f in funcs:
        by_name.setdefault(f.name, []).append(f)

    parent: Dict[ast.AST, ast.AST] = {}
    for f in funcs:
        for inner in ast.walk(f):
            if inner is not f and isinstance(
                inner, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and inner not in parent:
                parent[inner] = f

    roots: Set[ast.AST] = {
        f for f in funcs
        if any(_is_jit_decorator(d, ctx) for d in f.decorator_list)
    }
    # module-level `g = jax.jit(f)` marks f as a root
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and ctx.qualname(node.func) in _JIT_WRAPPERS:
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    roots.update(by_name.get(arg.id, []))

    calls: Dict[ast.AST, Set[str]] = {}
    for f in funcs:
        names: Set[str] = set()
        for inner in ast.walk(f):
            if isinstance(inner, ast.Call) and isinstance(inner.func, ast.Name):
                names.add(inner.func.id)
        calls[f] = names

    reachable: Set[ast.AST] = set()
    work = list(roots)
    while work:
        f = work.pop()
        if f in reachable:
            continue
        reachable.add(f)
        for name in calls.get(f, ()):
            for g in by_name.get(name, []):
                if g not in reachable:
                    work.append(g)
    # nested defs of reachable functions trace with them
    changed = True
    while changed:
        changed = False
        for child, par in parent.items():
            if par in reachable and child not in reachable:
                reachable.add(child)
                work.append(child)
                changed = True
        while work:
            f = work.pop()
            for name in calls.get(f, ()):
                for g in by_name.get(name, []):
                    if g not in reachable:
                        reachable.add(g)
                        work.append(g)
                        changed = True
    return reachable


def _repo_relative(path: str) -> str:
    """Stable posix-style path for findings/baselines: relative to the
    repo root (the directory holding the kaminpar_tpu package) when the
    file is under it, else relative to cwd, else absolute."""
    ap = os.path.abspath(path)
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    repo_root = os.path.dirname(pkg_root)
    for base in (repo_root, os.getcwd()):
        if ap.startswith(base.rstrip(os.sep) + os.sep):
            return os.path.relpath(ap, base).replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def lint_source(source: str, path: str,
                config: Optional[LintConfig] = None,
                index: Optional[cg.PackageIndex] = None) -> List[Finding]:
    """Lint one module's source text (path is used for reporting and
    path-scoped rules only; without an explicit package index a
    single-module one is built so same-file helpers still resolve)."""
    from . import rules as rules_mod
    from . import spmd as spmd_mod

    config = config or LintConfig()
    rel = _repo_relative(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                path=rel, rule="E0", line=int(e.lineno or 0), col=0,
                symbol="<module>",
                message=f"syntax error: {e.msg}",
                code="",
            )
        ]
    ctx = ModuleContext(rel, source, tree, config, index=index)
    per_line, per_file = _parse_suppressions(source)

    raw = rules_mod.run_rules(ctx) + spmd_mod.run_spmd_rules(ctx)
    findings: List[Finding] = []
    for f in raw:
        # E0 (syntax error) always passes the rule filter
        if f.rule not in config.rules and f.rule != "E0":
            continue
        if "ALL" in per_file or f.rule in per_file:
            continue
        line_rules = per_line.get(f.line, set())
        if "ALL" in line_rules or f.rule in line_rules:
            continue
        findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str, config: Optional[LintConfig] = None,
              index: Optional[cg.PackageIndex] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), path, config, index=index)


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py"):
            out.append(p)
    return out


def lint_paths(paths: Sequence[str],
               config: Optional[LintConfig] = None) -> List[Finding]:
    """Lint every .py file under the given paths (files or directories).

    Two passes: the first parses every file into one PackageIndex (the
    cross-module call graph), the second runs the rules with that index
    so span/guard analysis follows helpers across files.  When R9 is
    selected the cross-file schema-pin check runs once per invocation
    on top (it reads the repo's pin sites, not the linted paths)."""
    config = config or LintConfig()
    index = cg.PackageIndex()
    sources: List[Tuple[str, str]] = []
    for path in _iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        sources.append((path, source))
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue  # lint_source re-parses and reports E0
        index.add(_repo_relative(path), source, tree)

    findings: List[Finding] = []
    for path, source in sources:
        findings.extend(lint_source(source, path, config, index=index))
    if "R9" in config.rules:
        from . import schema_pins

        findings.extend(schema_pins.check_schema_pins(config))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
