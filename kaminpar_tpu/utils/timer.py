"""Hierarchical timer (TPU-native analog of kaminpar-common/timer.{h,cc}).

The reference keeps a global hierarchical timer singleton with SCOPED_TIMER
macros (kaminpar-common/timer.h:20-62).  Here we keep a lightweight tree of
named scopes; `scoped_timer` is a context manager.  Device work is made
observable by calling `jax.block_until_ready` at scope exit when requested.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Optional

from .. import telemetry


@dataclass
class TimerNode:
    name: str
    elapsed: float = 0.0
    count: int = 0
    children: Dict[str, "TimerNode"] = field(default_factory=dict)

    def child(self, name: str) -> "TimerNode":
        node = self.children.get(name)
        if node is None:
            node = TimerNode(name)
            self.children[name] = node
        return node


class Timer:
    """Hierarchical wall-clock timer tree.

    Mirrors the reference's global Timer (kaminpar-common/timer.h) but is an
    ordinary object; a module-level default instance stands in for the
    singleton.  Disabled timers are ~free.
    """

    def __init__(self, name: str = "root", enabled: bool = True) -> None:
        self.root = TimerNode(name)
        self._stack = [self.root]
        self._open_starts: list = []  # perf_counter stamps of open scopes
        self.enabled = enabled

    def reset(self) -> None:
        """Clear the tree.  A no-op while scopes are open: the library may
        run nested inside another pipeline (e.g. shm KaMinPar as the
        distributed driver's initial partitioner), and clearing mid-scope
        would orphan the open stack — the same global-singleton caveat the
        reference documents (README.MD:253-256)."""
        if len(self._stack) > 1:
            return
        self.root = TimerNode(self.root.name)
        self._stack = [self.root]
        self._open_starts = []

    def idle(self) -> bool:
        """True when no scope is open — i.e. not nested inside another
        pipeline.  Callers that reset process-global observability state
        (telemetry, stats) gate on this, matching reset()'s own guard."""
        return len(self._stack) == 1

    @contextmanager
    def scope(self, name: str, sync=None):
        """Time a named scope. `sync` may be a value to block_until_ready on exit."""
        if not self.enabled:
            yield
            return
        node = self._stack[-1].child(name)
        self._stack.append(node)
        tel = telemetry.enabled()
        entry_state = _span_entry_state() if tel else None
        start = time.perf_counter()
        self._open_starts.append(start)
        try:
            yield
        finally:
            # an emergency unwind() may have force-closed this scope
            # while the generator was suspended — don't double-account
            if self._stack and self._stack[-1] is node:
                sync_s = None
                if sync is not None:
                    t_sync = time.perf_counter()
                    try:
                        import jax

                        jax.block_until_ready(sync)
                    except Exception:
                        pass
                    sync_s = time.perf_counter() - t_sync
                end = time.perf_counter()
                node.elapsed += end - start
                node.count += 1
                if tel:
                    path = ".".join(n.name for n in self._stack[1:])
                    telemetry.record_span(
                        name, path, start, end - start,
                        **_span_exit_attrs(entry_state, sync_s),
                    )
                self._stack.pop()
                if self._open_starts:
                    self._open_starts.pop()

    def unwind(self) -> int:
        """Force-close every open scope, recording its elapsed time and
        span — the emergency path for an interrupt that surfaces from
        deep inside XLA (SIGINT during a jitted while_loop): without it
        the stack stays open, ``idle()`` lies, and the emergency run
        report renders a scope tree with un-accounted open nodes.
        Returns the number of scopes closed."""
        return self.unwind_to(1)

    def unwind_to(self, depth: int) -> int:
        """Force-close open scopes until the stack is back at ``depth``
        entries (the memory governor's per-rung unwind: a failed attempt
        must not leave ITS scopes open under the facade's, but the
        facade's own outer scopes stay).  ``unwind()`` is
        ``unwind_to(1)``."""
        closed = 0
        end = time.perf_counter()
        while len(self._stack) > max(1, depth):
            node = self._stack[-1]
            start = self._open_starts.pop() if self._open_starts else end
            node.elapsed += end - start
            node.count += 1
            if telemetry.enabled():
                path = ".".join(n.name for n in self._stack[1:])
                telemetry.record_span(
                    node.name, path, start, end - start, interrupted=True
                )
            self._stack.pop()
            closed += 1
        return closed

    def elapsed(self, *path: str) -> float:
        node = self.root
        for name in path:
            if name not in node.children:
                return 0.0
            node = node.children[name]
        return node.elapsed

    def render(self) -> str:
        lines = []

        def rec(node: TimerNode, depth: int) -> None:
            if depth > 0:
                lines.append(
                    f"{'  ' * depth}{node.name}: {node.elapsed:.4f} s"
                    + (f" ({node.count}x)" if node.count > 1 else "")
                )
            for child in node.children.values():
                rec(child, depth + 1)

        rec(self.root, 0)
        return "\n".join(lines)

    def render_machine(self) -> str:
        """One-line machine-readable dump: dotted-path=seconds pairs
        (the analog of the reference's machine-readable timer tree that
        backs its parseable TIME output, kaminpar-common/timer.h:135)."""
        parts = []

        def rec(node: TimerNode, path: str) -> None:
            for child in node.children.values():
                child_path = f"{path}.{child.name}" if path else child.name
                parts.append(f"{child_path}={child.elapsed:.6f}")
                rec(child, child_path)

        rec(self.root, "")
        return " ".join(parts)


def _span_entry_state() -> dict:
    """Snapshot the per-scope baselines for telemetry span attributes
    (only taken when telemetry is enabled; each section additionally
    gates on its own utility being enabled)."""
    state: dict = {}
    from . import heap_profiler, statistics

    if heap_profiler.profiling_enabled():
        import tracemalloc

        state["host_mem"] = tracemalloc.get_traced_memory()
    if statistics.enabled():
        state["counters"] = statistics.counters_snapshot()
    return state


def _span_exit_attrs(state: Optional[dict], sync_s: Optional[float]) -> dict:
    attrs: dict = {}
    if sync_s is not None:
        attrs["sync_s"] = round(sync_s, 6)
    if not state:
        return attrs
    from . import heap_profiler, statistics

    host_mem = state.get("host_mem")
    if host_mem is not None and heap_profiler.profiling_enabled():
        import tracemalloc

        cur0, peak0 = host_mem
        _, peak1 = tracemalloc.get_traced_memory()
        if peak1 > peak0:  # a new high-water mark was set inside the scope
            attrs["host_peak_bytes"] = int(peak1 - cur0)
        live = heap_profiler.live_device_bytes()
        if live:
            attrs["live_hbm_bytes"] = int(live)
    counters0 = state.get("counters")
    if counters0 is not None and statistics.enabled():
        delta = statistics.counters_delta(counters0)
        if delta:
            attrs["counters"] = delta
    return attrs


GLOBAL_TIMER = Timer()


@contextmanager
def scoped_timer(name: str, timer: Optional[Timer] = None, sync=None):
    t = timer if timer is not None else GLOBAL_TIMER
    with t.scope(name, sync=sync):
        yield


def aggregate_across_processes(timer: Optional[Timer] = None):
    """Per-device timer aggregation (kaminpar-dist/timer.cc analog).

    The reference finalizes its dist timer by reducing each scope's
    elapsed time across PEs (MPI min/avg/max) so a real-mesh run exposes
    imbalance between hosts.  The JAX analog reduces each scope across
    *processes* (multi-host SPMD: one process per host drives its local
    devices; per-scope wall times differ between hosts exactly like the
    reference's per-PE times).

    Returns {dotted_path: {"min": s, "avg": s, "max": s, "count": n}}.
    On a single-process run (this dev box, the CPU test mesh) every
    min == avg == max — the shape callers rely on is identical, so code
    written against it works unchanged on a real multi-host mesh.
    """
    t = timer if timer is not None else GLOBAL_TIMER

    paths: list = []
    values: list = []
    counts: list = []

    def rec(node: TimerNode, path: str) -> None:
        for child in node.children.values():
            child_path = f"{path}.{child.name}" if path else child.name
            paths.append(child_path)
            values.append(child.elapsed)
            counts.append(child.count)
            rec(child, child_path)

    rec(t.root, "")

    import numpy as np

    local = np.asarray(values, dtype=np.float64)
    try:
        from .platform import process_count

        nproc = process_count()
    except Exception:
        nproc = 1
    if nproc > 1 and len(local):
        # all hosts must call this with the SAME scope tree (same code
        # path), mirroring the reference's collective finalize()
        from jax.experimental import multihost_utils

        gathered = np.asarray(
            multihost_utils.process_allgather(local)
        ).reshape(nproc, -1)
        mins, avgs, maxs = (
            gathered.min(0), gathered.mean(0), gathered.max(0)
        )
    else:
        mins = avgs = maxs = local
    return {
        p: {
            "min": float(mins[i]),
            "avg": float(avgs[i]),
            "max": float(maxs[i]),
            "count": int(counts[i]),
        }
        for i, p in enumerate(paths)
    }


def render_aggregated(agg: dict) -> str:
    """Human-readable min/avg/max table (timer.cc's finalized output)."""
    lines = []
    for path, s in agg.items():
        depth = path.count(".")
        name = path.rsplit(".", 1)[-1]
        lines.append(
            f"{'  ' * (depth + 1)}{name}: min={s['min']:.4f} "
            f"avg={s['avg']:.4f} max={s['max']:.4f} s"
            + (f" ({s['count']}x)" if s["count"] > 1 else "")
        )
    return "\n".join(lines)
