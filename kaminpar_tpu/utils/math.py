"""Small math helpers (analog of kaminpar-common/math.h)."""

from __future__ import annotations


def ceil2(x: int) -> int:
    """Smallest power of two >= x (kaminpar-common/math.h ceil2)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def floor2(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x.bit_length() - 1)


def ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def round_up(x: int, multiple: int) -> int:
    return ceil_div(x, multiple) * multiple


# The shape-bucket padding policy moved to kaminpar_tpu.caching (the
# shared bucketing + bounded-cache policy module, ROADMAP item 5);
# re-exported here for its historical callers.
from ..caching import pad_size  # noqa: F401,E402


def split_integral(total: int, ratio: float) -> tuple[int, int]:
    """Split `total` into two integral parts by `ratio` (math.h split_integral)."""
    first = int(total * ratio + 0.5)
    first = max(0, min(total, first))
    return first, total - first
