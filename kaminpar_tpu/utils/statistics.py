"""Statistics counters (analog of KAMINPAR_ENABLE_STATISTICS / IFSTATS).

The reference gates detailed per-phase statistics behind a compile flag
(e.g. label_propagation.h:87,538, refinement/fm/batch_stats.cc).  Here a
process-global registry of named counters/series is toggled at runtime;
disabled stats are near-free.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List

_enabled = False
_counters: Dict[str, int] = defaultdict(int)
_series: Dict[str, List[float]] = defaultdict(list)


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    _counters.clear()
    _series.clear()


def count(name: str, delta: int = 1) -> None:
    """IFSTATS(counter++) analog."""
    if _enabled:
        _counters[name] += delta


def track(name: str, value: float) -> None:
    """Append to a named series (per-round cuts, move counts, ...)."""
    if _enabled:
        _series[name].append(float(value))


def get(name: str) -> int:
    return _counters.get(name, 0)


def series(name: str) -> List[float]:
    return list(_series.get(name, []))


def counters_snapshot() -> Dict[str, int]:
    """Copy of the counter map (telemetry span-entry baseline)."""
    return dict(_counters)


def counters_delta(snapshot: Dict[str, int]) -> Dict[str, int]:
    """Counters that changed since `snapshot` (span counters attribute)."""
    return {
        name: value - snapshot.get(name, 0)
        for name, value in _counters.items()
        if value != snapshot.get(name, 0)
    }


def as_dict() -> dict:
    """Counters + series summaries for the run report."""
    out: dict = dict(_counters)
    for name, vals in _series.items():
        if vals:
            out[name] = {
                "n": len(vals),
                "last": vals[-1],
                "min": min(vals),
                "max": max(vals),
            }
    return out


def render() -> str:
    lines = ["STATS"]
    for name in sorted(_counters):
        lines.append(f"  {name}={_counters[name]}")
    for name in sorted(_series):
        vals = _series[name]
        lines.append(
            f"  {name}: n={len(vals)} last={vals[-1]:g} "
            f"min={min(vals):g} max={max(vals):g}"
        )
    return "\n".join(lines)
