from . import math, rng, timer, logger  # noqa: F401
from .timer import GLOBAL_TIMER, Timer, scoped_timer  # noqa: F401
from .logger import OutputLevel, set_output_level  # noqa: F401
