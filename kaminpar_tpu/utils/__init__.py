from . import math, rng, timer, logger, assertions  # noqa: F401
from .timer import GLOBAL_TIMER, Timer, scoped_timer  # noqa: F401
from .logger import OutputLevel, set_output_level  # noqa: F401
from .assertions import (  # noqa: F401
    AssertionLevel,
    assertion_level,
    kassert,
    set_assertion_level,
)
