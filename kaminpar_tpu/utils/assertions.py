"""Leveled assertions (analog of kaminpar-common/assert.h KASSERT).

The reference compiles assertions at four levels — always / light /
normal / heavy (assert.h:39-50) — selected per build via
KAMINPAR_ASSERTION_LEVEL; heavy-level checks include full graph and
partition validation run inside the library (kaminpar-shm/kaminpar.cc:176,
kaminpar-dist/dkaminpar.cc:507-509).

Here the level is a process-global runtime knob (there is no compile
step to gate on): set it with `set_assertion_level()` or the
KAMINPAR_TPU_ASSERTION_LEVEL environment variable (name or number).
`kassert(cond, msg, level)` raises AssertionError when the active level
is at or above `level`.  `cond` may be a callable so heavy checks cost
nothing when disabled.
"""

from __future__ import annotations

import enum
import os
from typing import Callable, Union


class AssertionLevel(enum.IntEnum):
    """Mirrors kaminpar::assert levels (kaminpar-common/assert.h:39-50)."""

    ALWAYS = 0
    LIGHT = 1
    NORMAL = 2
    HEAVY = 3


def _level_from_env() -> AssertionLevel:
    raw = os.environ.get("KAMINPAR_TPU_ASSERTION_LEVEL", "")
    if not raw:
        return AssertionLevel.NORMAL
    try:
        return AssertionLevel(int(raw))
    except ValueError:
        try:
            return AssertionLevel[raw.strip().upper()]
        except KeyError:
            import warnings

            warnings.warn(
                f"invalid KAMINPAR_TPU_ASSERTION_LEVEL={raw!r} "
                f"(expected one of {[l.name for l in AssertionLevel]} or "
                f"0-3); using NORMAL",
                stacklevel=2,
            )
            return AssertionLevel.NORMAL


_ASSERTION_LEVEL = _level_from_env()


def assertion_level() -> AssertionLevel:
    return _ASSERTION_LEVEL


def set_assertion_level(level: Union[AssertionLevel, int, str]) -> None:
    global _ASSERTION_LEVEL
    if isinstance(level, str):
        level = AssertionLevel[level.strip().upper()]
    _ASSERTION_LEVEL = AssertionLevel(level)


def kassert(
    cond: Union[bool, Callable[[], bool]],
    msg: str = "",
    level: AssertionLevel = AssertionLevel.NORMAL,
) -> None:
    """Raise AssertionError if `cond` fails and `level` is active.

    Pass a zero-arg callable for expensive conditions — it is only
    evaluated when the level is enabled (the macro's compile-out analog).
    """
    if level > _ASSERTION_LEVEL:
        return
    ok = cond() if callable(cond) else cond
    if not ok:
        raise AssertionError(msg or "kassert failed")


def heavy_assertions_enabled() -> bool:
    return _ASSERTION_LEVEL >= AssertionLevel.HEAVY
