"""Lazy, ``JAX_PLATFORMS``-respecting device/backend gate.

Round-5 verdict Weak #2: the C-ABI embedded driver initialized the
``axon`` TPU-tunnel platform despite ``JAX_PLATFORMS=cpu`` in its
environment and hung the suite for 600 s while the tunnel was down.
The root hazard is *eager* backend discovery — any ``jax.devices()`` /
``jax.default_backend()`` call that runs before (or regardless of) the
platform restriction can spin up every registered plugin, including a
remote tunnel.

This module is the single place the package is allowed to ask jax about
devices/backends (lint rule R2 enforces that; see docs/static_analysis.md):

  * every query is lazy — ``import jax`` happens inside the call, never
    at module import;
  * when ``JAX_PLATFORMS`` (or the package's own ``KAMINPAR_TPU_PLATFORM``)
    names a platform, queries are restricted to that platform explicitly,
    so a misbehaving plugin is never initialized as a side effect;
  * ``default_backend()`` answers straight from the environment when it
    can, touching no backend at all — the cheapest possible path for
    callers that only branch on "cpu or not" (graphs/csr.shape_floors).

Platform resolution order: ``JAX_PLATFORMS`` wins; ``KAMINPAR_TPU_PLATFORM``
is the package-level override propagated into ``JAX_PLATFORMS`` before
first backend init (for embedding hosts whose environment cannot be
edited after process start).
"""

from __future__ import annotations

import os
import sys
from typing import Optional, Tuple

# last JAX_PLATFORMS value pushed into jax's config (None = never).
# Keyed by value, not a one-shot bool: an embedding host may set the
# override only after earlier gated queries already ran, and the gate
# must pick the change up on the next call.
_synced_value: Optional[str] = None


def ensure_platform_env() -> None:
    """Propagate ``KAMINPAR_TPU_PLATFORM`` into ``JAX_PLATFORMS``.

    Must run before jax initializes a backend; idempotent and free
    afterwards.  Called by every query below and by the C-ABI entry
    (capi.compute_from_pointers) before the pipeline imports.

    When jax is ALREADY imported (importing any kaminpar_tpu module
    pulls it in, and embedding hosts may set the override only just
    before the first compute call), the ``jax_platforms`` config has
    latched the env value from import time — pushing the restriction
    into the live config is the only thing that still works, and it
    does as long as no backend has initialized yet."""
    global _synced_value
    want = os.environ.get("KAMINPAR_TPU_PLATFORM", "").strip()
    if want and not os.environ.get("JAX_PLATFORMS", "").strip():
        os.environ["JAX_PLATFORMS"] = want
    effective = os.environ.get("JAX_PLATFORMS", "").strip()
    if effective == _synced_value:
        return
    _synced_value = effective
    if effective and "jax" in sys.modules:
        try:
            sys.modules["jax"].config.update("jax_platforms", effective)
        except Exception:
            pass  # backends already live: the explicit-backend queries
            # below still restrict every call this package makes


def _backend_init_guard():
    """Watchdog stage for backend discovery, armed only by the explicit
    env ceiling (KAMINPAR_TPU_HARD_DEADLINE_S) — backend init happens
    before any run-scoped budget exists.  Degrades to a no-op context
    while the resilience package is still bootstrapping."""
    try:
        from ..resilience import supervisor

        return supervisor.stage_guard(
            "backend-init", supervisor.env_ceiling()
        )
    except Exception:
        import contextlib

        return contextlib.nullcontext()


def requested_platforms() -> Tuple[str, ...]:
    """Platforms the environment restricts jax to ((), when unrestricted)."""
    ensure_platform_env()
    raw = os.environ.get("JAX_PLATFORMS", "").strip()
    return tuple(p.strip().lower() for p in raw.split(",") if p.strip())


def _primary_platform() -> Optional[str]:
    plats = requested_platforms()
    return plats[0] if plats else None


def devices(backend: Optional[str] = None) -> list:
    """``jax.devices()`` behind the gate.

    With a platform restriction in force the query names that platform
    explicitly, so only its backend is ever initialized.  Backend init
    is the package's canonical non-cooperative hang class (a downed
    axon tunnel blocked here for 600 s) — with
    ``KAMINPAR_TPU_HARD_DEADLINE_S`` set the init runs under an armed
    watchdog stage (resilience/supervisor.py): the hang is recorded
    with its ceiling, the liveness heartbeat stalls so external
    supervisors can act, and a ``StageHang`` is async-delivered the
    moment the blocked call returns to the interpreter."""
    ensure_platform_env()
    import jax

    backend = backend or _primary_platform()
    with _backend_init_guard():
        return jax.devices(backend) if backend else jax.devices()


def local_devices(backend: Optional[str] = None) -> list:
    """``jax.local_devices()`` behind the gate (see devices())."""
    ensure_platform_env()
    import jax

    backend = backend or _primary_platform()
    return (
        jax.local_devices(backend=backend) if backend
        else jax.local_devices()
    )


def device_count() -> int:
    return len(devices())


def default_backend() -> str:
    """The default platform name.

    When the environment already pins the platform this answers without
    touching jax at all — no plugin discovery, no tunnel."""
    plat = _primary_platform()
    if plat:
        return plat
    import jax

    return jax.default_backend()


def process_index() -> int:
    """``jax.process_index()``, degrading to 0 without a live backend."""
    ensure_platform_env()
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def process_count() -> int:
    """``jax.process_count()``, degrading to 1 without a live backend."""
    ensure_platform_env()
    try:
        import jax

        return int(jax.process_count())
    except Exception:
        return 1
