"""Scoped memory profiler (analog of kaminpar-common/heap_profiler.{h,cc}).

The reference interposes malloc (libc_memory_override.cc) and prints a
peak-memory tree per SCOPED_HEAP_PROFILER scope.  A Python/JAX process has
two memory domains to track:

  * host allocations — via tracemalloc (stdlib), scoped snapshots;
  * device (HBM) allocations — via jax.local_devices()[0].memory_stats()
    where the backend exposes them (TPU does; CPU returns None).

Profiling is off unless enabled (the reference compiles it out unless
KAMINPAR_ENABLE_HEAP_PROFILING); `enable()`/`disable()` toggle at runtime.
"""

from __future__ import annotations

import tracemalloc
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Optional

_enabled = False


@dataclass
class HeapNode:
    name: str
    peak_host_bytes: int = 0
    peak_device_bytes: int = 0
    live_device_bytes: int = 0
    count: int = 0
    children: Dict[str, "HeapNode"] = field(default_factory=dict)

    def child(self, name: str) -> "HeapNode":
        node = self.children.get(name)
        if node is None:
            node = HeapNode(name)
            self.children[name] = node
        return node


_root = HeapNode("root")
_stack = [_root]


def enable() -> None:
    global _enabled
    if not _enabled:
        tracemalloc.start()
        _enabled = True


def disable() -> None:
    global _enabled
    if _enabled:
        tracemalloc.stop()
        _enabled = False


def profiling_enabled() -> bool:
    return _enabled


def reset() -> None:
    global _root, _stack
    if len(_stack) > 1:
        return  # same open-scope guard as the timer
    _root = HeapNode("root")
    _stack = [_root]


def _device_peak_bytes() -> int:
    """Process-lifetime device high-water mark where the backend exposes
    it (TPU does via memory_stats; CPU returns 0)."""
    try:
        from .platform import local_devices

        stats = local_devices()[0].memory_stats()
        if stats:
            return int(
                stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
            )
    except Exception:
        pass
    return 0


def _live_device_bytes() -> int:
    """Sum of all live device-buffer sizes right now, via jax.live_arrays().

    Unlike the backend's lifetime high-water mark this is a *current*
    figure, so per-phase peaks can be measured even after an earlier
    phase set a larger process-wide peak — the number the compressed-mode
    memory contract (TeraPart, arXiv 2410.19119) is stated in.  Only
    persistent buffers are visible; intermediates inside a single jitted
    program are not (XLA frees them before the launch returns)."""
    try:
        import jax

        return sum(int(x.nbytes) for x in jax.live_arrays())
    except Exception:
        return 0


def sample_device_memory() -> int:
    """Record the current live-HBM figure into every OPEN scope.

    Call at phase boundaries (between device launches); returns the
    sampled byte count.  Scope entry/exit sample automatically, so this
    is only needed to catch peaks in the middle of a long scope."""
    if not _enabled:
        return 0
    live = _live_device_bytes()
    for node in _stack[1:]:
        node.live_device_bytes = max(node.live_device_bytes, live)
    return live


@contextmanager
def scoped_heap_profiler(name: str):
    """SCOPED_HEAP_PROFILER analog.

    Host: records how far above the scope-entry allocation level the
    traced peak rises while the scope is open (no reset_peak, so nested
    scopes don't clobber their parents' tracking).  Device: records the
    increase of the backend's lifetime high-water mark during the scope —
    if the scope stays below an earlier process-wide peak this reads 0,
    an inherent limit of peak-only counters."""
    if not _enabled:
        yield
        return
    node = _stack[-1].child(name)
    _stack.append(node)
    cur0, peak0 = tracemalloc.get_traced_memory()
    dev_peak0 = _device_peak_bytes()
    node.live_device_bytes = max(node.live_device_bytes, _live_device_bytes())
    try:
        yield
    finally:
        _, peak1 = tracemalloc.get_traced_memory()
        if peak1 > peak0:  # a new high-water mark was set inside the scope
            node.peak_host_bytes = max(node.peak_host_bytes, peak1 - cur0)
        node.peak_device_bytes = max(
            node.peak_device_bytes, _device_peak_bytes() - dev_peak0
        )
        node.live_device_bytes = max(
            node.live_device_bytes, _live_device_bytes()
        )
        node.count += 1
        _stack.pop()


def record(name: str, nbytes: int) -> None:
    """RECORD("name") analog: annotate a data structure's footprint."""
    if not _enabled:
        return
    node = _stack[-1].child(name)
    node.peak_host_bytes = max(node.peak_host_bytes, int(nbytes))
    node.count += 1


def live_device_bytes() -> int:
    """Public probe of the current live-HBM figure (telemetry spans
    attach this when heap profiling is enabled)."""
    return _live_device_bytes()


def tree_dict() -> dict:
    """The heap-profile tree as nested dicts (run-report `heap` section)."""

    def rec(node: HeapNode) -> dict:
        return {
            child.name: {
                "peak_host_bytes": child.peak_host_bytes,
                "peak_device_bytes": child.peak_device_bytes,
                "live_device_bytes": child.live_device_bytes,
                "count": child.count,
                "children": rec(child),
            }
            for child in node.children.values()
        }

    return rec(_root)


def _fmt(nbytes: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(nbytes) < 1024:
            return f"{nbytes:.0f} {unit}"
        nbytes /= 1024
    return f"{nbytes:.1f} TiB"


def render() -> str:
    """PRINT_HEAP_PROFILE analog."""
    lines = []

    def rec(node: HeapNode, depth: int) -> None:
        if depth > 0:
            extra = (
                f", device {_fmt(node.peak_device_bytes)}"
                if node.peak_device_bytes
                else ""
            )
            if node.live_device_bytes:
                extra += f", live HBM {_fmt(node.live_device_bytes)}"
            lines.append(
                f"{'  ' * depth}{node.name}: peak {_fmt(node.peak_host_bytes)}"
                f"{extra}"
            )
        for child in node.children.values():
            rec(child, depth + 1)

    rec(_root, 0)
    return "\n".join(lines) if lines else "(heap profiler: no scopes recorded)"
