"""Deterministic randomness (analog of kaminpar-common/random.{h,cc}).

The reference seeds a global RNG and derives per-thread instances
(random.h:21-76).  TPU-side we use jax.random PRNG keys derived from a global
seed; host-side we use numpy Generators derived from the same seed.  Both are
fully reproducible given the seed, which backs the rerun-determinism e2e test
(tests/endtoend/shm_endtoend_test.cc in the reference).
"""

from __future__ import annotations

import numpy as np

_SEED: int = 0
_HOST_COUNTER: int = 0


def set_seed(seed: int) -> None:
    global _SEED, _HOST_COUNTER
    _SEED = int(seed)
    _HOST_COUNTER = 0


def get_seed() -> int:
    return _SEED


def device_key(salt: int = 0):
    """A jax PRNG key derived from the global seed and a caller salt."""
    import jax

    return jax.random.key(np.uint32((_SEED * 0x9E3779B1 + salt) & 0xFFFFFFFF))


def host_rng(salt: int = 0) -> np.random.Generator:
    """A numpy Generator derived from the global seed and a caller salt."""
    return np.random.default_rng(np.uint64((_SEED << 20) ^ salt))


def fresh_host_rng() -> np.random.Generator:
    """Sequence of distinct host RNGs (analog of per-thread Random instances)."""
    global _HOST_COUNTER
    _HOST_COUNTER += 1
    return host_rng(_HOST_COUNTER)
