"""Leveled logger (analog of kaminpar-common/logger.{h,cc}).

The reference exposes LOG/LOG_WARNING/LOG_ERROR stream macros with a global
quiet switch (logger.h:225).  We wrap the stdlib logger with the same levels
plus an OutputLevel knob matching include/kaminpar-shm/kaminpar.h:32-38.
"""

from __future__ import annotations

import enum
import logging
import sys


class OutputLevel(enum.IntEnum):
    """Mirrors kaminpar::OutputLevel (include/kaminpar-shm/kaminpar.h:32-38)."""

    QUIET = 0
    PROGRESS = 1
    APPLICATION = 2
    EXPERIMENT = 3
    DEBUG = 4


class _DynamicStderrHandler(logging.StreamHandler):
    """Resolves sys.stderr at emit time (it may be redirected later)."""

    def __init__(self):
        super().__init__(stream=None)

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value):  # base __init__ assigns; ignore
        pass


_LOGGER = logging.getLogger("kaminpar_tpu")
if not _LOGGER.handlers:
    handler = _DynamicStderrHandler()
    handler.setFormatter(logging.Formatter("[kaminpar-tpu] %(message)s"))
    _LOGGER.addHandler(handler)
    # default OutputLevel is APPLICATION, so INFO must pass through
    _LOGGER.setLevel(logging.INFO)
    _LOGGER.propagate = False

_OUTPUT_LEVEL = OutputLevel.APPLICATION


def set_output_level(level: OutputLevel) -> None:
    global _OUTPUT_LEVEL
    _OUTPUT_LEVEL = OutputLevel(level)
    _LOGGER.setLevel(
        logging.ERROR
        if level == OutputLevel.QUIET
        else logging.INFO
        if level < OutputLevel.DEBUG
        else logging.DEBUG
    )


def output_level() -> OutputLevel:
    return _OUTPUT_LEVEL


def log(msg: str) -> None:
    if _OUTPUT_LEVEL >= OutputLevel.APPLICATION:
        _LOGGER.info(msg)


def log_progress(msg: str) -> None:
    if _OUTPUT_LEVEL >= OutputLevel.PROGRESS:
        _LOGGER.info(msg)


def log_debug(msg: str) -> None:
    if _OUTPUT_LEVEL >= OutputLevel.DEBUG:
        _LOGGER.debug(msg)


def log_warning(msg: str) -> None:
    _LOGGER.warning("Warning: %s", msg)


def log_error(msg: str) -> None:
    _LOGGER.error("Error: %s", msg)
