"""The CLI batch surface of the serving layer (``--serve-batch``).

Batch spec: a JSON file that is either a bare array of request objects
or ``{"config": {...}, "requests": [...]}``.  Each request object::

    {"graph": "gen:rgg2d;n=4096;avg_degree=8;seed=1" | "path/to.metis",
     "k": 8,                  # required
     "epsilon": 0.03,         # optional
     "deadline_s": 2.0,       # optional per-request anytime budget
     "hard_deadline_s": 20.0, # optional per-request HARD wall-clock
                              # ceiling (supervision contract)
     "priority": 0,           # optional, higher runs first
     "seed": 1,               # optional
     "id": "my-request"}      # optional stable id

Session-scoped request kinds (dynamic repartitioning,
kaminpar_tpu/dynamic/; inproc isolation only)::

    {"kind": "register",    "session": "s1", "graph": ..., "k": 8}
    {"kind": "mutate",      "session": "s1",
     "delta": {"edge_inserts": [[0, 5]], "edge_deletes": [[1, 2]]}}
    {"kind": "repartition", "session": "s1"}   # k defaults to the
                                               # session's k

``config`` keys map onto :class:`~kaminpar_tpu.serving.service.
ServiceConfig` fields (``max_queue_depth``, ``max_queued_cost``,
``max_request_cost``, ``result_cache_entries``, ``result_cache_bytes``,
``default_deadline_s``, and the supervision knobs ``isolation``,
``hard_deadline_s``, ``hard_deadline_factor``, ``worker_max_requests``,
``worker_rss_limit_bytes``, ``heartbeat_file``, ``metrics_file``; the
CLI flags ``--serve-isolation`` / ``--heartbeat-file`` /
``--metrics-file`` override the spec).

Exit-code contract: the PROCESS outcome, not the per-request outcomes —
isolated request failures and admission rejections still exit 0 (that is
the point of the isolation boundary); only an unreadable/invalid batch
file (exit 2) or a process-fatal error is nonzero.  Per-request verdicts
land on stdout (one ``SERVED`` line each), in the final ``SERVING``
summary line, and in the run report's ``serving`` section.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Tuple

from .service import PartitionRequest, PartitionService, ServiceConfig


class BatchSpecError(ValueError):
    """The batch file could not be parsed into requests."""


def load_batch(path: str) -> Tuple[List[PartitionRequest], ServiceConfig]:
    try:
        with open(path) as f:
            spec = json.load(f)
    except (OSError, ValueError) as e:
        raise BatchSpecError(f"unreadable batch spec {path!r}: {e}") from e
    if isinstance(spec, list):
        raw_requests, raw_config = spec, {}
    elif isinstance(spec, dict):
        raw_requests = spec.get("requests")
        raw_config = spec.get("config", {})
    else:
        raise BatchSpecError(f"{path}: batch spec must be a list or object")
    if not isinstance(raw_requests, list) or not raw_requests:
        raise BatchSpecError(f"{path}: no requests in batch spec")

    config = ServiceConfig()
    known = {f.name for f in dataclasses.fields(ServiceConfig)}
    for key, value in (raw_config or {}).items():
        if key not in known:
            raise BatchSpecError(f"{path}: unknown config key {key!r}")
        cur = getattr(config, key)
        if isinstance(cur, bool):
            # bool("false") is True — parse string booleans explicitly
            if isinstance(value, str):
                lowered = value.strip().lower()
                if lowered in ("1", "true", "yes", "on"):
                    value = True
                elif lowered in ("0", "false", "no", "off"):
                    value = False
                else:
                    raise BatchSpecError(
                        f"{path}: config {key!r} expects a boolean, "
                        f"got {value!r}"
                    )
            setattr(config, key, bool(value))
        else:
            try:
                setattr(config, key, type(cur)(value))
            except (TypeError, ValueError) as e:
                raise BatchSpecError(
                    f"{path}: bad value for config {key!r}: {e}"
                ) from e

    requests: List[PartitionRequest] = []
    for i, r in enumerate(raw_requests):
        kind = (r or {}).get("kind", "partition") \
            if isinstance(r, dict) else "partition"
        session_kind = kind in ("register", "mutate", "repartition")
        if not isinstance(r, dict) or (
            not session_kind and ("graph" not in r or "k" not in r)
        ):
            raise BatchSpecError(
                f"{path}: request #{i} needs at least 'graph' and 'k'"
            )
        if session_kind and not r.get("session"):
            raise BatchSpecError(
                f"{path}: request #{i} (kind={kind!r}) needs 'session'"
            )
        if kind == "register" and ("graph" not in r or "k" not in r):
            raise BatchSpecError(
                f"{path}: request #{i} (register) needs 'graph' and 'k'"
            )
        if kind == "mutate" and not isinstance(r.get("delta"), dict):
            raise BatchSpecError(
                f"{path}: request #{i} (mutate) needs a 'delta' object"
            )
        try:
            requests.append(PartitionRequest(
                graph=r.get("graph", ""),
                k=int(r.get("k", 0) or 0),
                kind=str(kind),
                session=str(r.get("session", "") or ""),
                delta=(r.get("delta")
                       if isinstance(r.get("delta"), dict) else None),
                # session kinds: an ABSENT epsilon means "the session's
                # contract" (register: the ctx default; repartition:
                # whatever the session was registered with), not the
                # stateless wire default
                epsilon=(
                    float(r["epsilon"])
                    if r.get("epsilon") is not None
                    else (None if session_kind else 0.03)
                ),
                deadline_s=(
                    float(r["deadline_s"])
                    if r.get("deadline_s") is not None else None
                ),
                hard_deadline_s=(
                    float(r["hard_deadline_s"])
                    if r.get("hard_deadline_s") is not None else None
                ),
                priority=int(r.get("priority", 0)),
                seed=(
                    int(r["seed"]) if r.get("seed") is not None else None
                ),
                request_id=str(r.get("id", "")) or f"req-{i + 1}",
            ))
        except (TypeError, ValueError) as e:
            # the exit-2 contract covers every malformed field, not just
            # missing ones — a bad spec must never traceback the CLI
            raise BatchSpecError(
                f"{path}: request #{i} has a malformed field: {e}"
            ) from e
    ids = [r.request_id for r in requests]
    dupes = sorted({i for i in ids if ids.count(i) > 1})
    if dupes:
        # duplicate ids would collide in the service's per-id cost/FIFO
        # maps and produce ambiguous report rows (an explicit "req-2"
        # can collide with a generated default just as easily)
        raise BatchSpecError(f"{path}: duplicate request id(s): {dupes}")
    return requests, config


def run_batch_cli(args, ctx) -> int:
    """Drive a batch through PartitionService for cli.main.  Telemetry
    and the fault-plan echo are already set up by the caller; this
    annotates the ``serving`` section and exports the requested report.
    """
    import sys
    import time

    from .. import telemetry

    try:
        requests, config = load_batch(args.serve_batch)
    except BatchSpecError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.serve_queue_depth is not None:
        config.max_queue_depth = int(args.serve_queue_depth)
    if args.serve_cost_cap is not None:
        config.max_queued_cost = float(args.serve_cost_cap)
    if getattr(args, "serve_isolation", None) is not None:
        config.isolation = str(args.serve_isolation)
    if getattr(args, "heartbeat_file", None):
        config.heartbeat_file = str(args.heartbeat_file)
    if getattr(args, "metrics_file", None):
        config.metrics_file = str(args.metrics_file)

    service = PartitionService(ctx, config, quiet=True)
    t0 = time.perf_counter()
    try:
        records = service.serve(requests)
    except KeyboardInterrupt:
        # a second Ctrl-C restored the default handler and surfaced here
        # mid-request: the single-shot emergency contract applies to the
        # batch too — unwind scopes, export a schema-valid report (with
        # the verdicts collected so far in its serving section), exit
        # 130.  cli._emergency_interrupt_exit annotates the anytime/
        # no-result sentinel sections and performs the export.
        from ..cli import _emergency_interrupt_exit

        service.annotate()
        service.close()
        return _emergency_interrupt_exit(args, t0)
    wall = time.perf_counter() - t0

    summary = service.annotate()
    service.close()  # release the supervised worker pool, if any
    if telemetry.enabled() and "result" not in telemetry.run_info():
        # the stream belongs to the LAST request; if it never produced a
        # result (failed/rejected/drained), the schema-required section
        # carries the explicit no-result sentinel (the emergency-report
        # idiom from cli._emergency_interrupt_exit) — per-request
        # results live in the serving section either way
        telemetry.annotate(
            result={"cut": -1, "imbalance": 0.0, "feasible": False}
        )
    if not args.quiet:
        for rec in records:
            extra = ""
            if rec.verdict in ("rejected", "failed"):
                extra = f" reason={rec.reason or rec.error}"
            elif rec.cached:
                extra = " cache=hit"
            print(
                f"SERVED id={rec.request_id} verdict={rec.verdict} "
                f"cut={rec.cut} feasible={int(rec.feasible)} "
                f"wall={rec.wall_s:.3f}s{extra}"
            )
        counts = summary["counts"]
        total_hist = (
            summary.get("latency", {}).get("phases", {}).get("total", {})
        )
        throughput = summary.get("throughput", {})
        print(
            "SERVING total={} served={} anytime={} degraded={} "
            "rejected={} failed={} worker_hang={} worker_crash={} "
            "cache_hit_rate={} p50_ms={} p95_ms={} rps={} "
            "queue_peak={} drained={} wall={:.3f}s".format(
                len(records), counts["served"], counts["anytime"],
                counts["degraded"], counts["rejected"], counts["failed"],
                counts.get("worker-hang", 0),
                counts.get("worker-crash", 0),
                summary["cache"]["hit_rate"],
                total_hist.get("p50_ms"), total_hist.get("p95_ms"),
                throughput.get("requests_per_second"),
                throughput.get("queue_peak"),
                int(summary["drained"]), wall,
            )
        )

    rc = telemetry.export_cli_outputs(
        args,
        extra_run={"serve_batch": args.serve_batch,
                   "requests": len(records),
                   "partition_seconds": round(wall, 3)},
        quiet=args.quiet,
    )
    return rc
