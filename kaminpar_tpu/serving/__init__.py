"""Partitioning-as-a-service: admission-controlled request queue with
per-request fault isolation and bounded caches.

ROADMAP item 2's workload — thousands of small-to-mid graphs per minute
at varying (k, eps) — needs a process model the one-shot facade never
had: many requests per process, one bad request failing *alone*, and
caches that stay bounded under sustained traffic.  This package
composes the PR 3–5 resilience primitives into that layer:

  * :class:`~kaminpar_tpu.serving.service.PartitionService` — a bounded
    request queue with admission control (queue-depth + estimated-cost
    caps; overload yields a structured ``rejected`` verdict, never an
    unbounded queue), per-request fault isolation (a malformed graph or
    a ``DeviceOOM`` fails that request with a schema-valid error record
    while the service keeps serving), a per-request-class circuit
    breaker, per-request deadlines arming the PR-5 anytime budget, and
    SIGTERM draining through the existing wind-down;
  * a **result cache** (:class:`kaminpar_tpu.caching.BoundedCache`)
    keyed by the PR-5 (graph fingerprint, ctx fingerprint) pair, with
    entry caps and byte-budget eviction, plus executable-bucket reuse
    accounting (:class:`kaminpar_tpu.caching.BucketTracker`) — cache
    hit-rate is a first-class report/bench metric;
  * the run report's ``serving`` section (schema v4): every request's
    verdict — ``served`` / ``anytime`` / ``degraded`` / ``rejected`` /
    ``failed`` — plus admission and cache statistics.

CLI surface: ``python -m kaminpar_tpu --serve-batch BATCH.json``
(serving/batch.py).  Operator contract: docs/robustness.md.
"""

from .service import (  # noqa: F401
    PartitionRequest,
    PartitionService,
    RequestRecord,
    ServiceConfig,
    VERDICTS,
)
