"""The PartitionService: bounded queue, admission control, isolation.

Execution model: requests are *submitted* (admission-controlled, cheap)
and then *executed* serially on the caller's thread — the accelerator is
one device, so the concurrency control is the bounded queue and the
admission policy, not a thread pool.  Every ``compute_partition`` call
runs inside a fault-isolation boundary: classified failures
(``resilience.errors``), malformed inputs (``io.GraphFormatError``), and
parameter errors produce a structured ``failed``/``rejected`` record for
*that request*; the service keeps serving.  Only genuinely
process-fatal conditions (``KeyboardInterrupt``, ``SystemExit``, the
checkpoint suite's ``SimulatedPreemption``) propagate.

Isolation guarantees (regression-tested in tests/test_serving.py):

  * resilience state is per-run by construction — each request's
    deadline/checkpoint state lives on a fresh
    :class:`~kaminpar_tpu.resilience.runstate.RunState`, so request N
    can neither consume request N-1's resume state nor inherit its stop
    verdict;
  * per-request contexts are deep copies of the service's base context
    with the checkpoint/resume knobs cleared — the serving result cache
    is the durability story here, and two requests can never share a
    manifest;
  * repeated crash-shaped failures in one request *class* (the
    executable bucket, i.e. padded (n, m, k)) open a per-class breaker:
    later requests of that class are rejected at admission instead of
    re-poisoning the device, while other classes keep serving.

Draining: a process-wide preemption signal (SIGTERM/SIGINT via the CLI
handlers, or :meth:`PartitionService.drain`) flips the service into
drain mode — the in-flight request finishes its mandatory tail through
the PR-5 wind-down (verdict ``anytime``), queued requests are rejected
with reason ``draining``, and every verdict still lands in the report.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .. import caching, telemetry
from ..resilience import errors as res_errors
from ..resilience import deadline as deadline_mod
from ..resilience import with_fallback
from ..resilience.policy import BREAKER_THRESHOLD

#: The verdict taxonomy, in severity order (docs/robustness.md).
VERDICTS = ("served", "anytime", "degraded", "rejected", "failed")

#: Estimated cost (bytes of device footprint, resilience/memory.py's
#: estimator) assumed for a request whose input cannot be sized without
#: loading it (an opaque file path of unknown content).
DEFAULT_COST = float(256 << 20)

#: Device-footprint bytes assumed per byte of an on-disk graph file
#: (admission never loads the file): text formats run ~8 bytes per edge
#: token against ~12-24 padded device bytes per edge plus working set.
FILE_COST_FACTOR = 4.0


@dataclass
class PartitionRequest:
    """One unit of service work: a graph source plus (k, eps) and QoS.

    ``graph`` may be a loaded HostGraph/CompressedHostGraph, a
    ``gen:...`` generator spec, or a file path (loaded inside the
    request's isolation boundary — a malformed file fails the request,
    not the submit call)."""

    graph: Any
    k: int
    #: balance tolerance; for SESSION kinds, None means "the session's
    #: contract" (register: the service ctx default; repartition: the
    #: epsilon the session was registered with)
    epsilon: Optional[float] = 0.03
    #: request kind (dynamic repartitioning, kaminpar_tpu/dynamic/):
    #: "partition" (the stateless default), or the session-scoped kinds
    #: "register" (create a session from ``graph`` + compute its
    #: initial partition), "mutate" (apply ``delta`` to ``session``),
    #: "repartition" (warm/cold repartition of ``session``; ``graph``
    #: is ignored for mutate/repartition)
    kind: str = "partition"
    #: session id for the session-scoped kinds
    session: str = ""
    #: DeltaBatch wire dict for kind="mutate" (parsed inside the
    #: isolation boundary — a malformed delta fails the request)
    delta: Optional[dict] = None
    deadline_s: Optional[float] = None  # per-request anytime budget
    #: explicit per-request HARD wall-clock ceiling (supervision
    #: contract): overrides the service-level hard_deadline_s and the
    #: factor-derived ceiling; None = resolve from the config
    hard_deadline_s: Optional[float] = None
    priority: int = 0  # higher runs first
    seed: Optional[int] = None
    request_id: str = ""

    _counter = itertools.count(1)

    def __post_init__(self) -> None:
        if not self.request_id:
            self.request_id = f"req-{next(self._counter)}"


@dataclass
class RequestRecord:
    """One request's verdict — the row that lands in the run report's
    ``serving.requests`` array (and, for rejected requests, the whole
    story: nothing else ever ran)."""

    request_id: str
    verdict: str  # one of VERDICTS
    reason: str = ""  # rejection/failure/anytime reason
    error: str = ""  # structured error type for failed requests
    detail: str = ""  # truncated error message
    k: int = 0
    n: int = -1  # -1: input never resolved (rejected before load)
    m: int = -1
    cut: int = -1
    imbalance: float = 0.0
    feasible: bool = False
    gate_valid: Optional[bool] = None
    cached: bool = False
    bucket: str = ""  # executable bucket key "n_pad/m_pad/k_pad"
    degraded_sites: List[str] = field(default_factory=list)
    wall_s: float = 0.0
    # the hard wall-clock ceiling the request ran under (supervision
    # contract, resilience/supervisor.py); None = no ceiling armed
    hard_ceiling_s: Optional[float] = None
    # per-phase latency breakdown in ms (admission_wait / resolve /
    # compute / gate) — the per-request rows behind serving.latency
    phases: Dict[str, float] = field(default_factory=dict)
    partition: Optional[np.ndarray] = None  # library callers only

    def to_dict(self) -> dict:
        d = {
            "request_id": self.request_id,
            "verdict": self.verdict,
            "k": int(self.k),
            "n": int(self.n),
            "m": int(self.m),
            "cut": int(self.cut),
            "imbalance": float(self.imbalance),
            "feasible": bool(self.feasible),
            "cached": bool(self.cached),
            "wall_s": round(float(self.wall_s), 4),
        }
        for key in ("reason", "error", "detail", "bucket"):
            v = getattr(self, key)
            if v:
                d[key] = v
        if self.gate_valid is not None:
            d["gate_valid"] = bool(self.gate_valid)
        if self.hard_ceiling_s is not None:
            d["hard_ceiling_s"] = round(float(self.hard_ceiling_s), 3)
        if self.degraded_sites:
            d["degraded_sites"] = list(self.degraded_sites)
        if self.phases:
            d["phases"] = dict(self.phases)
        return d


@dataclass
class ServiceConfig:
    """Admission + cache policy knobs (docs/robustness.md)."""

    max_queue_depth: int = 64
    #: total estimated cost admitted but not yet run.  Since the
    #: memory-governor PR the unit is BYTES of estimated device
    #: footprint (resilience/memory.estimate_run_bytes) — one sizing
    #: model shared by `request-too-large`/`cost-cap` and the
    #: `insufficient-memory` rule — where it used to be work units
    #: (~ n + m); the flag semantics are unchanged, only the unit
    max_queued_cost: float = float(8 << 30)
    #: a single request estimated larger than this (bytes) is rejected
    #: outright
    max_request_cost: float = float(4 << 30)
    result_cache_entries: int = 128
    result_cache_bytes: int = 256 << 20
    #: default per-request budget when the request carries none (0: none)
    default_deadline_s: float = 0.0
    #: consecutive crash-shaped failures before a request class is
    #: rejected at admission (mirrors the site breaker threshold)
    breaker_threshold: int = BREAKER_THRESHOLD
    #: keep partitions on the records (library callers; the CLI drops
    #: them — a 16-request batch of 1M-node graphs is 64 MB of labels)
    keep_partitions: bool = False
    #: execution isolation (docs/robustness.md, supervision contract):
    #: "inproc" (default) runs compute on the caller's thread exactly
    #: as before; "process" runs it in a supervised worker subprocess
    #: — a worker hang is SIGKILLed past the hard ceiling (verdict
    #: `failed`/`worker-hang`), a worker death is classified
    #: (`worker-crash`), and the service keeps draining either way
    isolation: str = "inproc"
    #: explicit per-request hard wall-clock ceiling in seconds (0 =
    #: derive from the cooperative deadline via hard_deadline_factor,
    #: or KAMINPAR_TPU_HARD_DEADLINE_S)
    hard_deadline_s: float = 0.0
    #: hard ceiling = max(factor * budget, budget + grace) for requests
    #: that carry a cooperative deadline (resilience/supervisor.py)
    hard_deadline_factor: float = 10.0
    #: recycle the warm worker after this many requests (leak
    #: containment; process isolation only)
    worker_max_requests: int = 32
    #: ... or once its peak RSS exceeds this watermark (bytes; 0 = off)
    worker_rss_limit_bytes: float = float(4 << 30)
    #: liveness heartbeat file (also settable via --heartbeat-file /
    #: KAMINPAR_TPU_HEARTBEAT_FILE); "" = disabled
    heartbeat_file: str = ""
    #: live metrics export file (Prometheus text format, rewritten
    #: atomically on a cadence; also settable via --metrics-file /
    #: KAMINPAR_TPU_METRICS_FILE); "" = disabled — the registry stays
    #: dormant and costs one attribute read per producer call
    metrics_file: str = ""


class PartitionService:
    """Admission-controlled, fault-isolated partitioning service."""

    def __init__(self, ctx: Any = "default",
                 config: Optional[ServiceConfig] = None,
                 quiet: bool = True) -> None:
        from ..context import Context
        from ..presets import create_context_by_preset_name

        if isinstance(ctx, str):
            ctx = create_context_by_preset_name(ctx)
        assert isinstance(ctx, Context)
        self.base_ctx = ctx
        self.config = config or ServiceConfig()
        self.quiet = quiet
        # guards the queue/bookkeeping maps so concurrent submit()
        # producers are safe; execution itself stays serial and unlocked
        self._lock = threading.Lock()
        self._queue: List[PartitionRequest] = []
        self._queued_cost: Dict[str, float] = {}
        self._records: List[RequestRecord] = []
        self._seq = itertools.count()
        self._order: Dict[str, int] = {}  # request_id -> FIFO tiebreak
        self._submit_class: Dict[str, str] = {}  # id -> admission class
        self._admission_rejected = 0  # excludes drain-time rejections
        self._result_cache = caching.BoundedCache(
            max_entries=self.config.result_cache_entries,
            max_bytes=self.config.result_cache_bytes,
        )
        # the memory governor sheds this cache first under HBM pressure
        # (resilience/memory.shed_caches; weakly held — dies with us)
        from ..resilience import memory as _memory_mod

        _memory_mod.register_shed_target(self._result_cache)
        self._buckets = caching.BucketTracker()
        # per-request-class (executable bucket) crash counters
        self._class_failures: Dict[str, int] = {}
        # dynamic graph sessions (kaminpar_tpu/dynamic/): id -> live
        # GraphSession, plus the decision rows for the report's
        # `dynamic` section.  Session requests run inproc only — the
        # supervised worker exchange ships graphs by value and cannot
        # carry mutable session state (documented; admission rejects
        # with `session-isolation` under process isolation).
        self._sessions: Dict[str, Any] = {}
        self._dynamic_decisions: List[dict] = []
        self._drained = False
        # serving latency metrics (telemetry/perf.py Histogram): one
        # streaming histogram per request phase plus a per-class (bucket)
        # rollup — the report's serving.latency section.  Windowed with
        # the records (reset_records), so a long-lived service reports
        # per-window percentiles instead of frozen lifetime averages.
        from ..telemetry.perf import Histogram

        self._latency: Dict[str, Histogram] = {
            phase: Histogram()
            for phase in ("admission_wait", "resolve", "compute",
                          "gate", "total")
        }
        self._class_latency: Dict[str, Histogram] = {}
        self._submit_t: Dict[str, float] = {}  # id -> submit stamp
        # supervised execution (resilience/supervisor.py): in process
        # mode compute runs in a warm worker subprocess under the hard
        # wall-clock watchdog — spawned lazily on the first executed
        # request, recycled on the configured request/RSS watermarks
        from ..resilience import supervisor as supervisor_mod

        if self.config.isolation not in ("inproc", "process"):
            raise ValueError(
                f"unknown isolation mode {self.config.isolation!r} "
                "(want 'inproc' or 'process')"
            )
        self._pool = (
            supervisor_mod.WorkerPool(
                max_requests=int(self.config.worker_max_requests),
                rss_limit_bytes=int(self.config.worker_rss_limit_bytes),
            )
            if self.config.isolation == "process" else None
        )
        if self.config.heartbeat_file:
            supervisor_mod.set_heartbeat(self.config.heartbeat_file)
        # live metrics export (telemetry/metrics.py): dormant unless a
        # file is configured here or via KAMINPAR_TPU_METRICS_FILE —
        # configure() resolves arg-then-env and is a no-op otherwise
        from ..telemetry import metrics as metrics_mod

        metrics_mod.configure(self.config.metrics_file or None)
        # throughput accounting for summary()["throughput"] and the
        # SERVING stdout line — service-local (NOT the live registry),
        # so it works with metrics export dormant
        self._rate = metrics_mod.WindowRate(
            "serving_rps", "service-local throughput window")
        self._queue_peak = 0
        self._occupancy_sum = 0.0
        self._occupancy_n = 0
        # per-request trace ids (telemetry/tracing.py): created at
        # admission when tracing is active, popped when the verdict's
        # phase spans are recorded
        self._trace_ids: Dict[str, str] = {}

    # -- admission -----------------------------------------------------

    def _estimate(self, req: PartitionRequest):
        """(cost, n, m) for admission — cost is the ESTIMATED DEVICE
        BYTES of the request (resilience/memory.estimate_run_bytes for
        the padded bucket), the same sizing model the memory budget is
        enforced in; n/m are -1 when unknown without loading the input
        (opaque file path — sized from the file length, never a load)."""
        k = int(req.k or 2)

        def price(n: int, m: int) -> float:
            # governor pricing at admission: external-scheme services
            # cost the STREAM state (O(n) vectors + chunk buffers +
            # the coarse handoff target), everything else the padded
            # in-core bucket — one sizing model per scheme, shared
            # with the insufficient-memory rule below
            from ..context import PartitioningMode
            from ..resilience import memory as memory_mod

            if self.base_ctx.partitioning.mode == PartitioningMode.EXTERNAL:
                ext = self.base_ctx.external
                return float(memory_mod.estimate_stream_bytes(
                    n, int(ext.chunk_edges), k
                ))
            return float(memory_mod.estimate_run_bytes(n, m, k))

        kind = getattr(req, "kind", "partition")
        if kind in ("mutate", "repartition"):
            # session kinds are sized from the LIVE session graph (the
            # one state admission can know without loading anything); a
            # mutate is host-side CSR work — priced nominally so the
            # cost cap still counts it
            sess = self._sessions.get(req.session or "")
            if sess is None:
                return DEFAULT_COST, -1, -1
            n, m = int(sess.graph.n), int(sess.graph.m)
            if kind == "mutate":
                return float(1 << 20), n, m
            return price(n, m), n, m
        g = req.graph
        if hasattr(g, "n") and hasattr(g, "m"):
            n, m = int(g.n), int(g.m)
            return price(n, m), n, m
        if isinstance(g, str) and g.startswith("gen:"):
            try:
                from ..graphs.factories import parse_gen_spec

                _, kw = parse_gen_spec(g)
                n = int(kw.get("n") or (
                    int(kw.get("x", 1)) * int(kw.get("y", 1))
                    * int(kw.get("z", 1))
                ))
                m = int(kw.get("m") or n * float(kw.get("avg_degree", 8)))
                return price(n, m), n, m
            except Exception:
                return DEFAULT_COST, -1, -1
        if isinstance(g, str):
            try:
                import os

                return (
                    max(float(os.path.getsize(g)) * FILE_COST_FACTOR, 1.0),
                    -1, -1,
                )
            except OSError:
                return DEFAULT_COST, -1, -1
        return DEFAULT_COST, -1, -1

    def _class_key(self, n: int, m: int, k: int) -> str:
        if n < 0:
            return "unsized"
        return "/".join(str(x) for x in caching.bucket_key(n, m, k))

    def _admission_reason(self, req: PartitionRequest, cost: float,
                          cls: str, n: int = -1, m: int = -1) -> str:
        """First violated admission rule, or "" to admit.  The injected
        `serving-admit` fault routes through the policy wrapper so the
        chaos suite sees the standard `degraded` event."""
        admitted = with_fallback(
            lambda: True, lambda exc: False,
            site="serving-admit", where=req.request_id,
        )
        if not admitted:
            return "fault-injected"
        if deadline_mod.draining():
            return "draining"
        kind = getattr(req, "kind", "partition")
        if kind not in ("partition", "register", "mutate", "repartition"):
            return "invalid-parameters"
        if kind != "partition":
            if self._pool is not None:
                # the worker exchange ships graphs by value; mutable
                # session state cannot round-trip it (docs/robustness.md
                # "Dynamic sessions") — refuse structurally instead of
                # silently running outside the supervision boundary
                return "session-isolation"
            if not req.session:
                return "invalid-parameters"
            if kind == "register" and req.session in self._sessions:
                return "duplicate-session"
            if kind in ("mutate", "repartition") \
                    and req.session not in self._sessions:
                return "unknown-session"
            if kind == "mutate" and not isinstance(req.delta, dict):
                return "invalid-parameters"
        if kind in ("partition", "register") and (
            req.k is None or int(req.k) < 1
        ):
            return "invalid-parameters"
        if req.request_id in self._queued_cost:
            # a pending duplicate would corrupt the cost/FIFO maps keyed
            # by request_id; completed ids may be reused (re-submission)
            return "duplicate-id"
        if len(self._queue) >= self.config.max_queue_depth:
            return "queue-full"
        if cost > self.config.max_request_cost:
            return "request-too-large"
        if sum(self._queued_cost.values()) + cost > self.config.max_queued_cost:
            return "cost-cap"
        # memory-budget admission (resilience/memory.py): a request
        # whose MINIMUM device-resident footprint (the rung-2
        # spilled-hierarchy estimate) exceeds the declared budget could
        # only ever be served at the streamed/host rungs — orders slower
        # than the service's latency contract — so it is rejected with a
        # structured verdict instead.  Sized without loading the graph;
        # unsized (file-backed) inputs skip the rule, consistent with
        # the 'unsized' breaker-class convention.  Single-shot CLI runs
        # still degrade through every rung.
        if n >= 0:
            from ..context import PartitioningMode
            from ..resilience import memory as memory_mod

            budget = memory_mod.budget_bytes(self.base_ctx)
            if budget and memory_mod.governor_enabled():
                # external-scheme services price the STREAM, not the
                # resident hierarchy — that pricing asymmetry is the
                # scheme's whole point: a graph far over the in-core
                # budget is admissible as long as the O(n) vectors +
                # one floor chunk fit (kaminpar_tpu/external/)
                if (
                    self.base_ctx.partitioning.mode
                    == PartitioningMode.EXTERNAL
                ):
                    floor = memory_mod.min_streamable_bytes(
                        n, int(req.k or 2)
                    )
                else:
                    floor = memory_mod.min_serveable_bytes(
                        n, m, int(req.k or 2)
                    )
                if floor > budget:
                    return "insufficient-memory"
        if self._class_failures.get(cls, 0) >= self.config.breaker_threshold:
            return "breaker-open"
        return ""

    def submit(self, req: PartitionRequest) -> Optional[RequestRecord]:
        """Admission-check one request.  Returns the ``rejected`` record
        when the request is refused (already appended to the batch
        records); None when it was queued."""
        cost, n, m = self._estimate(req)
        cls = self._class_key(n, m, int(req.k or 0))
        with self._lock:
            reason = self._admission_reason(req, cost, cls, n, m)
            if reason:
                rec = RequestRecord(
                    request_id=req.request_id, verdict="rejected",
                    reason=reason, k=int(req.k or 0), n=n, m=m,
                )
                self._records.append(rec)
                self._admission_rejected += 1
                depth = len(self._queue)
            else:
                self._queue.append(req)
                self._queued_cost[req.request_id] = cost
                self._order[req.request_id] = next(self._seq)
                self._submit_class[req.request_id] = cls
                self._submit_t[req.request_id] = time.perf_counter()
                depth = len(self._queue)
                self._queue_peak = max(self._queue_peak, depth)
                rec = None
        from ..telemetry import metrics as metrics_mod
        from ..telemetry import tracing

        if metrics_mod.enabled():
            metrics_mod.set_gauge(
                "kmp_queue_depth", depth,
                "Requests admitted but not yet executed.")
            metrics_mod.set_gauge(
                "kmp_queue_peak", self._queue_peak,
                "Peak queue depth observed this process.")
        if rec is not None:
            telemetry.event(
                "serving", action="rejected", request=req.request_id,
                reason=reason, queue_depth=depth,
            )
            if metrics_mod.enabled():
                metrics_mod.inc(
                    "kmp_requests_total",
                    "Requests by final verdict.", 1.0,
                    verdict="rejected")
        else:
            tid = tracing.new_trace(
                req.request_id, k=int(req.k or 0),
                kind=getattr(req, "kind", "partition"),
            )
            if tid:
                self._trace_ids[req.request_id] = tid
                tracing.span(
                    tid, "admission", duration_s=0.0,
                    cls=cls, queue_depth=depth,
                )
        return rec

    # -- execution -----------------------------------------------------

    def run_pending(self) -> List[RequestRecord]:
        """Execute the queue serially (priority desc, then FIFO).  A
        drain signal observed between requests rejects the remainder;
        the batch always returns one record per request."""
        done: List[RequestRecord] = []
        while True:
            with self._lock:
                if not self._queue:
                    break
                self._queue.sort(
                    key=lambda r: (-r.priority, self._order[r.request_id])
                )
                req = self._queue.pop(0)
                self._queued_cost.pop(req.request_id, None)
                self._order.pop(req.request_id, None)
                cls_submit = self._submit_class.pop(req.request_id, "")
                submit_t = self._submit_t.pop(req.request_id, None)
            if deadline_mod.draining():
                self._drained = True
                rec = RequestRecord(
                    request_id=req.request_id, verdict="rejected",
                    reason="draining", k=int(req.k or 0),
                )
            else:
                wait_s = (
                    time.perf_counter() - submit_t
                    if submit_t is not None else 0.0
                )
                # deep layers (dist rank rollup, dynamic session
                # commits) attach their spans to the current trace
                from ..telemetry import tracing

                tracing.set_current(
                    self._trace_ids.get(req.request_id, "")
                )
                try:
                    rec = self._execute(req, cls_submit, wait_s)
                finally:
                    tracing.set_current("")
            with self._lock:
                self._records.append(rec)
            done.append(rec)
            self._request_done(rec)
        return done

    def _request_done(self, rec: RequestRecord) -> None:
        """Per-verdict bookkeeping after one EXECUTED request (drain
        rejections included; admission rejections are counted at
        submit): throughput window, batch occupancy, the live metrics
        registry, and the trace verdict annotation."""
        from ..telemetry import metrics as metrics_mod
        from ..telemetry import tracing

        self._rate.mark()
        occ = None
        if rec.bucket and rec.n >= 0:
            try:
                # bucket_key pads n+1 node slots; occupancy is how much
                # of the padded executable this request actually filled
                n_pad = int(rec.bucket.split("/")[0])
                occ = min(1.0, float(rec.n + 1) / float(n_pad))
            except (ValueError, ZeroDivisionError):
                occ = None
            if occ is not None:
                self._occupancy_sum += occ
                self._occupancy_n += 1
        tid = self._trace_ids.pop(rec.request_id, "")
        if tid:
            tracing.annotate(
                tid, verdict=rec.verdict,
                **({"reason": rec.reason} if rec.reason else {}),
            )
        if not metrics_mod.enabled():
            return
        metrics_mod.inc(
            "kmp_requests_total", "Requests by final verdict.", 1.0,
            verdict=rec.verdict)
        metrics_mod.mark(
            "kmp_requests_per_second",
            "Requests completed, per second over a sliding window.")
        with self._lock:
            depth = len(self._queue)
        metrics_mod.set_gauge(
            "kmp_queue_depth", depth,
            "Requests admitted but not yet executed.")
        if self._occupancy_n:
            metrics_mod.set_gauge(
                "kmp_batch_occupancy",
                round(self._occupancy_sum / self._occupancy_n, 4),
                "Mean padded-executable fill fraction of executed "
                "requests.")
        metrics_mod.set_gauge(
            "kmp_cache_hit_rate",
            float(self._result_cache.stats()["hit_rate"]),
            "Result-cache hit rate (lifetime).")
        metrics_mod.set_gauge(
            "kmp_breaker_open_classes",
            sum(1 for v in self._class_failures.values()
                if v >= self.config.breaker_threshold),
            "Request classes currently rejected by the crash breaker.")
        if self._pool is not None:
            for event, v in self._pool.stats.items():
                metrics_mod.set_gauge(
                    "kmp_worker_pool", float(v),
                    "Worker-pool lifecycle counters "
                    "(spawned/recycled/killed/crashed/requests).",
                    event=str(event))
        from ..resilience import runstate as runstate_mod

        gov = runstate_mod.current().memory
        if gov is not None:
            metrics_mod.set_gauge(
                "kmp_governor_rung", float(gov.rung),
                "Memory-governor degradation rung of the last run.")
        from ..resilience import supervisor as supervisor_mod

        hb = supervisor_mod.heartbeat_path()
        if hb:
            try:
                import os

                metrics_mod.set_gauge(
                    "kmp_heartbeat_age_seconds",
                    round(max(0.0, time.time() - os.path.getmtime(hb)),
                          3),
                    "Seconds since the liveness heartbeat file "
                    "advanced.")
            except OSError:
                pass

    def serve(self, requests) -> List[RequestRecord]:
        """Drive a whole batch: submit() each request, draining the
        queue whenever the next submission would trip the queue-depth or
        aggregate-cost cap — a batch is ONE producer, so backpressure
        means "run what is queued first", not "reject the tail" (the
        caps still reject outright for concurrent submit() producers and
        for single oversized requests).  Returns this batch's records
        (admission rejections included, in order)."""
        start = len(self._records)
        for req in requests:
            # session-kind requests depend on earlier requests having
            # EXECUTED (a mutate needs its register/mutate predecessors
            # committed, and priority sorting must not reorder a
            # session's chain) — drain the queue before admitting one
            if self._queue and (
                self._would_overflow(req)
                or getattr(req, "kind", "partition") != "partition"
            ):
                self.run_pending()
            self.submit(req)
        self.run_pending()
        return self._records[start:]

    def _would_overflow(self, req: PartitionRequest) -> bool:
        cost, _, _ = self._estimate(req)
        with self._lock:
            return (
                len(self._queue) >= self.config.max_queue_depth
                or sum(self._queued_cost.values()) + cost
                > self.config.max_queued_cost
            )

    def _resolve_graph(self, source):
        """Load/generate the input INSIDE the isolation boundary."""
        if isinstance(source, str):
            if source.startswith("gen:"):
                from ..graphs.factories import generate

                return generate(source)
            from .. import io as io_mod

            return io_mod.load_graph(source)
        if not (hasattr(source, "n") and hasattr(source, "m")):
            raise res_errors.AdmissionRejected(
                f"request graph is neither a graph object nor a "
                f"path/spec string: {type(source).__name__}"
            )
        return source

    def _request_ctx(self, req: PartitionRequest):
        """Per-request context: the base tree deep-copied, resilience
        re-scoped to this request (no cross-request checkpoint state;
        the per-request deadline arms the PR-5 anytime budget)."""
        ctx = self.base_ctx.copy()
        ctx.resilience.checkpoint_dir = ""
        ctx.resilience.resume = False
        budget = (
            req.deadline_s if req.deadline_s is not None
            else self.config.default_deadline_s
        )
        ctx.resilience.time_budget = float(budget or 0.0)
        if req.seed is not None:
            ctx.seed = int(req.seed)
        # stamp the partition target so the ctx fingerprint (and with it
        # the result-cache key) covers (k, eps) before setup runs
        ctx.partition.k = int(req.k)
        if req.epsilon is not None:  # None = keep the ctx default
            ctx.partition.epsilon = float(req.epsilon)
        return ctx

    def _hard_ceiling(self, req: PartitionRequest) -> Optional[float]:
        """The request's hard wall-clock ceiling (supervision contract):
        explicit service override first, else derived from the
        cooperative per-request deadline (or the env override) via
        resilience/supervisor.hard_ceiling.  None = no ceiling."""
        from ..resilience import supervisor as supervisor_mod

        if req.hard_deadline_s is not None and req.hard_deadline_s > 0:
            return float(req.hard_deadline_s)
        if self.config.hard_deadline_s > 0:
            return float(self.config.hard_deadline_s)
        budget = (
            req.deadline_s if req.deadline_s is not None
            else self.config.default_deadline_s
        )
        return supervisor_mod.hard_ceiling(
            budget or 0.0, factor=self.config.hard_deadline_factor
        )

    def _cache_lookup(self, key, req: PartitionRequest,
                      pre_degraded: List[str]):
        """Result-cache get through the `serving-cache` site: an
        injected fault forces a miss AND evicts the key (both documented
        degradation modes at once — deterministic for the chaos suite).
        The engaged site is recorded in ``pre_degraded`` because the
        facade resets the telemetry stream at compute entry — the event
        emitted here would otherwise vanish before the verdict is cut.
        """
        def forced_miss(exc):
            self._result_cache.evict(key)
            pre_degraded.append("serving-cache")
            return None

        cached = with_fallback(
            lambda: self._result_cache.get(key), forced_miss,
            site="serving-cache", where=req.request_id,
        )
        if cached is None:
            return None
        from ..resilience import integrity
        from ..resilience.errors import IntegrityViolation

        # entries written before the digest upgrade verify vacuously
        if len(cached) == 3:
            part, metrics, digest = cached
        else:
            part, metrics = cached
            digest = ""
        # `cache-poison` chaos flips a bit of the array ABOUT to be
        # served; the stored content digest is what catches it.  A
        # poisoned entry must read as a forced miss + evict — served
        # stale bytes are the one cache failure mode worse than a miss.
        part = integrity.chaos_flip_array("cache-poison", part)
        try:
            integrity.verify_digest(
                digest, part,
                what=f"result-cache:{req.request_id}",
                site="cache-poison",
            )
        except IntegrityViolation:
            self._result_cache.evict(key)
            pre_degraded.append("cache-poison")
            from ..utils.logger import log_warning

            log_warning(
                f"serving[{req.request_id}]: result-cache entry failed "
                "digest verification; evicted, recomputing"
            )
            return None
        return part, metrics

    def _note_failure(self, rec: RequestRecord, exc: BaseException,
                      cls: str, cls_submit: str) -> None:
        """THE failure bookkeeping of the isolation boundary — shared
        by the stateless (:meth:`_execute`) and session-kind
        (:meth:`_execute_session`) paths so the verdict/reason
        taxonomy, the breaker exemptions, and the telemetry surface
        can never drift apart: classify, stamp the reason
        (worker-crash / worker-hang|stage-hang / malformed-input /
        exception), advance the per-class breaker for crash-shaped
        failures only."""
        err = res_errors.classify(exc, site="")
        rec.verdict = "failed"
        rec.error = type(err if err is not None else exc).__name__
        rec.detail = str(exc)[:300]
        # supervision verdicts (resilience/supervisor.py) carry their
        # own reason taxonomy: a SIGKILLed hung worker reads
        # `worker-hang`, a dead worker `worker-crash`, and an
        # in-process watchdog overrun `stage-hang` — everything else
        # keeps the malformed-input/exception split.  (err.site is NOT
        # trusted for hangs: a hang landing inside a guarded primary
        # may carry that site's stamp.)
        if isinstance(err, res_errors.WorkerCrash):
            rec.reason = "worker-crash"
        elif isinstance(err, res_errors.StageHang):
            rec.reason = (
                "worker-hang" if self._pool is not None
                else "stage-hang"
            )
        elif isinstance(err, res_errors.IntegrityViolation):
            # detected silent data corruption that exhausted the
            # retry-from-barrier ladder (or a corrupted worker reply):
            # its own taxonomy row — NOT malformed-input, the input was
            # fine; the bytes rotted in compute or exchange
            rec.reason = "corrupt-result"
        else:
            rec.reason = (
                "malformed-input" if _input_shaped(exc)
                else "exception"
            )
        # crash-shaped failures advance the request-class breaker;
        # refusal-shaped degradations (breaker_relevant=False) and
        # malformed inputs do not — a bad file/delta says nothing about
        # the next request of the same shape.  Latched under BOTH the
        # resolved executable bucket and the admission-time estimate
        # class (for file-backed inputs those differ), so the admission
        # check actually observes the count.
        crash = (
            err.breaker_relevant if err is not None
            else not _input_shaped(exc)
        )
        if (
            isinstance(err, res_errors.DeviceOOM)
            and not err.rungs_exhausted
        ):
            # a ladder-retryable OOM indicts the budget, not the
            # request class — only rung EXHAUSTION is crash-shaped
            crash = False
        if crash:
            for c in {cls, cls_submit} - {""}:
                self._class_failures[c] = (
                    self._class_failures.get(c, 0) + 1
                )
        telemetry.event(
            "serving", action="failed", request=rec.request_id,
            error=rec.error, reason=rec.reason,
        )
        from ..utils.logger import log_warning

        log_warning(
            f"serving[{rec.request_id}]: request failed in isolation "
            f"({rec.error}: {rec.detail[:120]}); service continues"
        )

    def _execute_session(self, req: PartitionRequest,
                         cls_submit: str = "",
                         wait_s: float = 0.0) -> RequestRecord:
        """The session-scoped request kinds (register / mutate /
        repartition, kaminpar_tpu/dynamic/) under the same isolation
        boundary, breaker, and latency accounting as stateless
        requests.  Sessions are created only on a fully successful
        register; a failed mutate leaves the session at its pre-delta
        state (the CSR patch is computed pure before either commit
        path)."""
        from ..resilience.checkpoint import SimulatedPreemption
        from ..utils.logger import OutputLevel

        t0 = time.perf_counter()
        rec = RequestRecord(
            request_id=req.request_id, verdict="failed",
            k=int(req.k or 0),
        )
        cls = cls_submit or "unsized"
        resolve_s = compute_s = 0.0
        try:
            if req.kind == "register":
                from ..dynamic import GraphSession
                from ..kaminpar import KaMinPar

                graph = self._resolve_graph(req.graph)
                resolve_s = time.perf_counter() - t0
                sess = GraphSession(
                    req.session, graph, k=int(req.k))
                rec.n, rec.m = int(graph.n), int(graph.m)
                bucket = self._buckets.observe(rec.n, rec.m, int(req.k))
                rec.bucket = "/".join(str(x) for x in bucket)
                cls = self._class_key(rec.n, rec.m, int(req.k))
                rec.hard_ceiling_s = self._hard_ceiling(req)
                ctx = self._request_ctx(req)
                solver = KaMinPar(ctx)
                if self.quiet:
                    solver.set_output_level(OutputLevel.QUIET)
                solver.set_graph(sess.graph)
                # the session REMEMBERS its balance contract: later
                # repartitions without an explicit epsilon reuse it
                sess.epsilon = (
                    float(req.epsilon) if req.epsilon is not None
                    else None
                )
                t_c0 = time.perf_counter()
                part = solver.compute_partition(
                    k=int(req.k), epsilon=req.epsilon,
                    seed=req.seed,
                )
                compute_s = time.perf_counter() - t_c0
                metrics = solver.result_metrics(sess.graph, part)
                rec.gate_valid = telemetry.gate_verdict()
                sess.commit_partition(
                    part, int(metrics["cut"]),
                    gate_valid=rec.gate_valid)
                self._sessions[req.session] = sess
                rec.cut = int(metrics["cut"])
                rec.imbalance = float(metrics["imbalance"])
                rec.feasible = bool(metrics["feasible"])
                rec.degraded_sites = sorted({
                    e.attrs.get("site", "")
                    for e in telemetry.events("degraded")
                } - {""})
                anytime = solver.last_anytime
                self._dynamic_decisions.append({
                    "session": sess.id, "kind": "register",
                    "mode": "cold", "drift": None, "cut_before": None,
                    "cut": rec.cut, "feasible": rec.feasible,
                    "stable": None, "escalated": False, "seeded": 0,
                    "wall_s": round(compute_s, 4),
                    "warm_wall_s": None,
                    "cold_wall_s": round(compute_s, 4),
                    **({"gate_valid": rec.gate_valid}
                       if rec.gate_valid is not None else {}),
                })
            elif req.kind == "mutate":
                from ..dynamic import DeltaBatch

                sess = self._sessions[req.session]
                batch = DeltaBatch.from_dict(req.delta)
                resolve_s = time.perf_counter() - t0
                # mutate runs no compute, so the telemetry stream is
                # NOT reset for this request — snapshot the degraded
                # count so a previous request's degradations are not
                # attributed to this one
                deg_before = len(telemetry.events("degraded"))
                t_c0 = time.perf_counter()
                info = sess.apply(batch)
                compute_s = time.perf_counter() - t_c0
                rec.degraded_sites = sorted({
                    e.attrs.get("site", "")
                    for e in telemetry.events("degraded")[deg_before:]
                } - {""})
                rec.k = int(sess.k)
                rec.n, rec.m = info["n"], info["m"]
                rec.bucket = info["bucket"]
                cls = self._class_key(rec.n, rec.m, int(sess.k))
                rec.reason = (
                    "in-place" if info["in_place"] else "rebuild")
                anytime = None
                rec.cut = (
                    -1 if sess.last_cut is None else int(sess.last_cut))
                rec.feasible = sess.last_cut is not None
            else:  # repartition
                from ..dynamic import repartition as _repartition

                sess = self._sessions[req.session]
                resolve_s = time.perf_counter() - t0
                k = int(req.k or sess.k)
                rec.k = k
                rec.n, rec.m = int(sess.graph.n), int(sess.graph.m)
                bucket = self._buckets.observe(rec.n, rec.m, k)
                rec.bucket = "/".join(str(x) for x in bucket)
                cls = self._class_key(rec.n, rec.m, k)
                rec.hard_ceiling_s = self._hard_ceiling(req)
                ctx = self._request_ctx(req)
                ctx.partition.k = k  # req.k may be 0 = "the session's k"
                # epsilon defaults to the SESSION's contract (set at
                # register), not the wire default — caps and the diff
                # gate must match what the session was partitioned under
                eps = (
                    req.epsilon if req.epsilon is not None
                    else sess.epsilon
                )
                t_c0 = time.perf_counter()
                outcome = _repartition(
                    sess, ctx, k=k, epsilon=eps,
                    seed=req.seed, quiet=self.quiet,
                )
                compute_s = time.perf_counter() - t_c0
                rec.cut = int(outcome.cut)
                rec.imbalance = float(outcome.imbalance)
                rec.feasible = bool(outcome.feasible)
                rec.gate_valid = outcome.gate_valid
                rec.degraded_sites = list(outcome.degraded_sites)
                anytime = outcome.anytime
                self._dynamic_decisions.append({
                    **outcome.to_row(sess.id), "kind": "repartition",
                })
        except (KeyboardInterrupt, SystemExit, SimulatedPreemption):
            raise  # process-fatal by contract; never a request verdict
        except BaseException as exc:  # the isolation boundary
            self._note_failure(rec, exc, cls, cls_submit)
            rec.wall_s = time.perf_counter() - t0
            self._observe_latency(
                rec, wait_s, resolve_s,
                max(rec.wall_s - resolve_s, 0.0), 0.0,
            )
            return rec

        for c in {cls, cls_submit} - {""}:
            self._class_failures.pop(c, None)
        if anytime:
            rec.verdict = "anytime"
            if not rec.reason:
                rec.reason = str(anytime.get("reason") or "")
            if rec.reason in ("sigterm", "sigint", "draining"):
                self._drained = True
        elif rec.degraded_sites:
            rec.verdict = "degraded"
        else:
            rec.verdict = "served"
        rec.wall_s = time.perf_counter() - t0
        self._observe_latency(rec, wait_s, resolve_s, compute_s, 0.0)
        # the trace CARRIES the session identity: every register /
        # mutate / repartition against one GraphSession is findable by
        # its session attr (and repartition traces say warm vs cold)
        from ..telemetry import tracing

        tid = self._trace_ids.get(req.request_id, "")
        if tid:
            extra = {}
            if req.kind == "repartition" and self._dynamic_decisions:
                extra["mode"] = self._dynamic_decisions[-1].get("mode")
            tracing.annotate(
                tid, session=req.session, session_kind=req.kind,
                **extra,
            )
        telemetry.event(
            "dynamic", action=req.kind, request=req.request_id,
            session=req.session, verdict=rec.verdict,
        )
        return rec

    def _execute(self, req: PartitionRequest,
                 cls_submit: str = "",
                 wait_s: float = 0.0) -> RequestRecord:
        from ..kaminpar import KaMinPar
        from ..resilience.checkpoint import SimulatedPreemption
        from ..utils import timer
        from ..utils.logger import OutputLevel

        if getattr(req, "kind", "partition") != "partition":
            return self._execute_session(req, cls_submit, wait_s)

        t0 = time.perf_counter()
        rec = RequestRecord(
            request_id=req.request_id, verdict="failed", k=int(req.k),
        )
        cls = cls_submit or "unsized"
        pre_degraded: List[str] = []
        resolve_s = compute_s = gate_s = 0.0
        try:
            graph = self._resolve_graph(req.graph)
            resolve_s = time.perf_counter() - t0
            rec.n, rec.m = int(graph.n), int(graph.m)
            ctx = self._request_ctx(req)
            key = caching.result_cache_key(graph, ctx)
            cached = self._cache_lookup(key, req, pre_degraded)
            if cached is not None:
                part, metrics = cached
                rec.verdict = "served"
                rec.cached = True
                rec.cut = int(metrics["cut"])
                rec.imbalance = float(metrics["imbalance"])
                rec.feasible = bool(metrics["feasible"])
                rec.gate_valid = metrics.get("gate_valid")
                rec.partition = part if self.config.keep_partitions else None
                rec.wall_s = time.perf_counter() - t0
                self._observe_latency(rec, wait_s, resolve_s, 0.0, 0.0)
                telemetry.event(
                    "serving", action="cache-hit", request=req.request_id,
                )
                return rec
            bucket = self._buckets.observe(rec.n, rec.m, int(req.k))
            rec.bucket = "/".join(str(x) for x in bucket)
            cls = self._class_key(rec.n, rec.m, int(req.k))
            rec.hard_ceiling_s = self._hard_ceiling(req)

            winfo = None
            solver = None
            t_c0 = time.perf_counter()
            if self._pool is not None:
                # supervised worker execution: compute runs in the
                # spawned worker under the hard wall-clock watchdog; a
                # hang is SIGKILLed and surfaces as StageHang (site
                # `worker-hang`), a worker death as WorkerCrash — both
                # land in the isolation boundary below like any other
                # classified failure, and the queue keeps draining
                tid = self._trace_ids.get(req.request_id, "")
                part, winfo = self._pool.run_request(
                    req.request_id, req.graph, graph, ctx,
                    k=int(req.k),
                    epsilon=float(req.epsilon if req.epsilon is not None
                                  else 0.03),
                    seed=req.seed, ceiling_s=rec.hard_ceiling_s,
                    trace=bool(tid),
                )
                gate_s = float(winfo.get("gate_s") or 0.0)
                if winfo.get("ledger"):
                    # fold the worker's h2d/d2h bytes into this
                    # process's ledger: the transfers happened on the
                    # request's behalf, just across the containment
                    # boundary (telemetry/ledger marshal contract)
                    from ..telemetry import ledger

                    ledger.absorb(winfo["ledger"])
                if tid and winfo.get("trace_spans"):
                    # marshal the worker-side spans into this request's
                    # timeline: the spawn/ship overhead span first, the
                    # worker's own scopes re-based after it
                    from ..telemetry import tracing

                    tracing.record_worker_reply(
                        tid, winfo["trace_spans"], t_c0,
                        time.perf_counter() - t_c0,
                        float(winfo.get("wall_s") or 0.0),
                    )
            else:
                solver = KaMinPar(ctx)
                if self.quiet:
                    solver.set_output_level(OutputLevel.QUIET)
                solver.set_graph(graph)
                part = solver.compute_partition(
                    k=int(req.k),
                    epsilon=float(req.epsilon if req.epsilon is not None
                                  else 0.03),
                    seed=req.seed,
                )
                # the gate runs inside compute_partition under its own
                # top-level scope; the per-run timer reset at compute
                # entry makes this elapsed figure THIS request's gate
                # time
                gate_s = timer.GLOBAL_TIMER.elapsed("output-gate")
            compute_s = max(time.perf_counter() - t_c0 - gate_s, 0.0)
        except (KeyboardInterrupt, SystemExit, SimulatedPreemption):
            raise  # process-fatal by contract; never a request verdict
        except BaseException as exc:  # the isolation boundary
            self._note_failure(rec, exc, cls, cls_submit)
            rec.wall_s = time.perf_counter() - t0
            # failures carry latency too (whatever phases completed) —
            # a timeout-shaped failure mode must be visible in p99
            self._observe_latency(
                rec, wait_s, resolve_s,
                max(rec.wall_s - resolve_s - gate_s, 0.0), gate_s,
            )
            return rec

        # success path: harvest the per-request telemetry (inproc: the
        # facade reset the stream at compute entry, so everything in it
        # belongs to this request; process: the worker harvested ITS
        # stream the same way and marshalled the harvest back)
        for c in {cls, cls_submit} - {""}:
            self._class_failures.pop(c, None)
        if winfo is not None:
            metrics = dict(winfo["metrics"])
            rec.gate_valid = winfo.get("gate_valid")
            worker_degraded = set(winfo.get("degraded_sites") or [])
            anytime = winfo.get("anytime")
        else:
            metrics = solver.result_metrics(graph, part)
            rec.gate_valid = telemetry.gate_verdict()
            worker_degraded = {
                e.attrs.get("site", "")
                for e in telemetry.events("degraded")
            }
            anytime = solver.last_anytime
        rec.cut = int(metrics["cut"])
        rec.imbalance = float(metrics["imbalance"])
        rec.feasible = bool(metrics["feasible"])
        rec.degraded_sites = sorted(
            (worker_degraded | set(pre_degraded)) - {""}
        )
        if anytime:
            rec.verdict = "anytime"
            rec.reason = str(anytime.get("reason") or "")
            if rec.reason in ("sigterm", "sigint", "draining"):
                self._drained = True
        elif rec.degraded_sites:
            rec.verdict = "degraded"
        else:
            rec.verdict = "served"
        rec.partition = part if self.config.keep_partitions else None
        rec.wall_s = time.perf_counter() - t0
        self._observe_latency(rec, wait_s, resolve_s, compute_s, gate_s)
        if rec.verdict == "served" and rec.feasible:
            # only clean full-effort results are worth replaying; an
            # anytime/degraded answer must not be served to a request
            # that had the time to do better
            from ..resilience import integrity

            part_arr = np.asarray(part)
            # entry digest stamped at put, verified on every hit
            # (resilience/integrity.py exchange contract)
            self._result_cache.put(
                key,
                (part_arr,
                 {**metrics, "gate_valid": rec.gate_valid},
                 integrity.content_digest(part_arr)),
                nbytes=part_arr.nbytes,
            )
        return rec

    def _observe_latency(self, rec: RequestRecord, wait_s: float,
                         resolve_s: float, compute_s: float,
                         gate_s: float) -> None:
        """Fold one request's phase walls into the streaming histograms
        (overall per-phase + per-class total) and stamp the per-request
        breakdown onto its record.  `total` includes the admission wait
        — the latency a CALLER observes, not just the execution."""
        from ..telemetry.perf import Histogram

        total_s = rec.wall_s + wait_s
        phases = {
            "admission_wait": wait_s,
            "resolve": resolve_s,
            "compute": compute_s,
            "gate": gate_s,
            "total": total_s,
        }
        for name, v in phases.items():
            self._latency[name].record(v)
        rec.phases = {
            f"{name}_ms": round(v * 1000.0, 3)
            for name, v in phases.items()
        }
        # request-trace phase spans (telemetry/tracing.py): every
        # execution path funnels through here, so the trace timeline
        # covers queue-wait/resolve/compute/gate for all verdicts; the
        # gate phase includes the greedy balance repair (gate.py)
        tid = self._trace_ids.get(rec.request_id, "")
        if tid:
            from ..telemetry import tracing

            t_exec = time.perf_counter() - rec.wall_s
            tracing.span(tid, "queue-wait", start=t_exec - wait_s,
                         duration_s=wait_s)
            tracing.span(tid, "resolve", start=t_exec,
                         duration_s=resolve_s)
            tracing.span(tid, "compute", start=t_exec + resolve_s,
                         duration_s=compute_s)
            tracing.span(tid, "gate", start=t_exec + resolve_s
                         + compute_s, duration_s=gate_s)
        from ..telemetry import metrics as metrics_mod

        if metrics_mod.enabled():
            metrics_mod.observe(
                "kmp_request_latency_seconds", total_s,
                "End-to-end request latency (admission wait included).")
        # cache hits never touch an executable (rec.bucket stays empty)
        # but still belong to their shape class for the rollup
        cls = rec.bucket or self._class_key(rec.n, rec.m, int(rec.k or 0))
        hist = self._class_latency.get(cls)
        if hist is None:
            hist = self._class_latency[cls] = Histogram()
        hist.record(total_s)

    def latency_summary(self) -> dict:
        """The report's ``serving.latency`` section: per-phase
        histograms (p50/p95/p99 over log-spaced buckets) and the
        per-class rollup joined with executable-bucket reuse counts."""
        sightings = self._buckets.per_bucket()
        classes = {}
        for cls, hist in self._class_latency.items():
            snap = hist.snapshot()
            seen = sightings.get(cls, 0)
            classes[cls] = {
                "requests": snap["count"],
                "p50_ms": snap["p50_ms"],
                "p95_ms": snap["p95_ms"],
                "p99_ms": snap["p99_ms"],
                "mean_ms": snap["mean_ms"],
                # executable utilization of the class: how often its
                # compiled programs were reused rather than rebuilt
                "executable_sightings": int(seen),
                "executable_reuse": (
                    round((seen - 1) / seen, 4) if seen else 0.0
                ),
            }
        return {
            "phases": {
                name: hist.snapshot()
                for name, hist in self._latency.items()
            },
            "classes": classes,
        }

    # -- drain / reporting ---------------------------------------------

    def drain(self, reason: str = "draining") -> None:
        """Programmatic drain: queued requests will be rejected with
        ``draining``; an in-flight run winds down at its next barrier
        (the SIGTERM handlers reach the same state process-wide)."""
        deadline_mod.request_stop(reason)

    @property
    def records(self) -> List[RequestRecord]:
        with self._lock:
            return list(self._records)

    def reset_records(self) -> List[RequestRecord]:
        """Detach and return the accumulated verdict records (with the
        admission-rejection counter).  The records list is the report
        surface — every verdict must land in a report — so it is never
        pruned implicitly; a long-lived service exports a report per
        batch window and then resets, which bounds host memory under
        sustained traffic.  Cache/bucket/breaker state is kept, but
        their WINDOW counters and the latency histograms restart with
        the records — each exported window carries its own hit rates
        and percentiles instead of averages frozen by hours of history.
        """
        with self._lock:
            out = self._records
            self._records = []
            self._admission_rejected = 0
            for hist in self._latency.values():
                hist.reset()
            self._class_latency.clear()
        self._result_cache.begin_window()
        self._buckets.begin_window()
        return out

    def result_cache_stats(self) -> dict:
        return self._result_cache.stats()

    def summary(self) -> dict:
        """The run report's ``serving`` section (schema v4)."""
        with self._lock:
            records = list(self._records)
            admission_rejected = self._admission_rejected
        counts = {v: 0 for v in VERDICTS}
        for rec in records:
            counts[rec.verdict] = counts.get(rec.verdict, 0) + 1
        # supervision verdicts surface in the counts next to the five
        # verdict keys — only when nonzero, so `sum(counts over the
        # verdict keys) == len(requests)` stays true for consumers that
        # sum the whole dict on an unsupervised batch
        for reason_key in ("worker-hang", "worker-crash"):
            hit = sum(1 for r in records if r.reason == reason_key)
            if hit:
                counts[reason_key] = hit
        result_stats = self._result_cache.stats()
        return {
            "enabled": True,
            "requests": [r.to_dict() for r in records],
            "counts": counts,
            "admission": {
                "max_queue_depth": self.config.max_queue_depth,
                "max_queued_cost": float(self.config.max_queued_cost),
                "max_request_cost": float(self.config.max_request_cost),
                # drain-time rejections carry the same verdict but never
                # passed admission; this counter is admission's alone
                "rejected": admission_rejected,
            },
            "cache": {
                "result": result_stats,
                "executable": self._buckets.stats(),
                "hit_rate": result_stats["hit_rate"],
            },
            "latency": self.latency_summary(),
            "throughput": self.throughput_summary(),
            "drained": bool(self._drained),
        }

    def throughput_summary(self) -> dict:
        """Live throughput figures (the SERVING stdout line and the
        bench harness read these): sliding-window requests/second, the
        peak queue depth this process observed, and the mean padded-
        executable fill fraction (None until a sized request ran)."""
        return {
            "requests_per_second": round(float(self._rate.rate()), 3),
            "queue_peak": int(self._queue_peak),
            "batch_occupancy": (
                round(self._occupancy_sum / self._occupancy_n, 4)
                if self._occupancy_n else None
            ),
        }

    def dynamic_summary(self) -> dict:
        """The run report's ``dynamic`` section (schema v11) for this
        service: live session rows + the decision log
        (kaminpar_tpu/dynamic/driver.summarize; {'enabled': False}
        when no session request ever ran)."""
        from ..dynamic import summarize

        with self._lock:
            sessions = list(self._sessions.values())
            decisions = list(self._dynamic_decisions)
        return summarize(sessions, decisions)

    def supervision_summary(self) -> dict:
        """The run report's ``supervision`` section (schema v10) for
        this service: worker-pool lifecycle counters, the hang log,
        heartbeat state, watchdog stats, and the isolation mode."""
        from ..resilience import supervisor as supervisor_mod

        return supervisor_mod.summary(
            pool=self._pool, isolation=self.config.isolation
        )

    def close(self) -> None:
        """Shut down the supervised worker pool (process isolation);
        a plain inproc service has nothing to release.  Idempotent.
        Flushes a final metrics scrape so a scraper never misses the
        tail of a short-lived service."""
        if self._pool is not None:
            self._pool.shutdown()
        from ..telemetry import metrics as metrics_mod

        if metrics_mod.enabled():
            metrics_mod.write_now()

    def annotate(self) -> dict:
        """Stamp the serving + supervision sections into the telemetry
        run info (call AFTER the last request — compute_partition
        resets the stream at entry) and return the serving section."""
        s = self.summary()
        telemetry.annotate(
            serving=s, supervision=self.supervision_summary(),
            dynamic=self.dynamic_summary(),
        )
        return s


def _input_shaped(exc: BaseException) -> bool:
    """Failures that indict the request's INPUT, not the process or the
    request class: format errors, missing files, bad parameters."""
    from ..io import GraphFormatError

    return isinstance(
        exc, (GraphFormatError, ValueError, OSError, KeyError, TypeError)
    ) and not isinstance(exc, res_errors.DegradationError)
