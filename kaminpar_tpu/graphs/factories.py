"""Programmatic graph factories.

Mirrors the reference's test fixtures (tests/shm/graph_factories.h:
make_grid_graph, make_path, make_star, ...) but lives in the package so
tools, benchmarks, and tests share them.  Also provides synthetic RMAT/RGG
generators standing in for the reference's external KaGen streaming input
(kaminpar-io/dist_skagen.cc).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .host import HostGraph, from_edge_list
from ..utils import rng as rng_mod


def make_empty_graph(n: int = 0) -> HostGraph:
    return HostGraph(np.zeros(n + 1, dtype=np.int64), np.zeros(0, dtype=np.int32))


def make_path(n: int, edge_weight: int = 1) -> HostGraph:
    if n <= 1:
        return make_empty_graph(n)
    e = np.stack([np.arange(n - 1), np.arange(1, n)], axis=1)
    w = np.full(n - 1, edge_weight, dtype=np.int64)
    return from_edge_list(n, e, w)


def make_cycle(n: int) -> HostGraph:
    if n <= 2:
        return make_path(n)
    e = np.stack([np.arange(n), (np.arange(n) + 1) % n], axis=1)
    return from_edge_list(n, e)


def make_star(n_leaves: int) -> HostGraph:
    """Node 0 is the hub."""
    n = n_leaves + 1
    e = np.stack([np.zeros(n_leaves, dtype=np.int64), np.arange(1, n)], axis=1)
    return from_edge_list(n, e)


def make_grid_graph(rows: int, cols: int) -> HostGraph:
    """4-neighbor grid (tests/shm/graph_factories.h make_grid_graph)."""
    idx = np.arange(rows * cols).reshape(rows, cols)
    right = np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    down = np.stack([idx[:-1, :].ravel(), idx[1:, :].ravel()], axis=1)
    return from_edge_list(rows * cols, np.concatenate([right, down]))


def make_complete_graph(n: int, edge_weight: int = 1) -> HostGraph:
    iu = np.triu_indices(n, k=1)
    e = np.stack(iu, axis=1)
    w = np.full(len(e), edge_weight, dtype=np.int64)
    return from_edge_list(n, e, w)


def make_complete_bipartite_graph(a: int, b: int) -> HostGraph:
    left = np.repeat(np.arange(a), b)
    right = a + np.tile(np.arange(b), a)
    return from_edge_list(a + b, np.stack([left, right], axis=1))


def make_isolated_graph(n: int) -> HostGraph:
    return make_empty_graph(n)


def make_matching_graph(num_pairs: int) -> HostGraph:
    e = np.stack(
        [2 * np.arange(num_pairs), 2 * np.arange(num_pairs) + 1], axis=1
    )
    return from_edge_list(2 * num_pairs, e)


# Shared generator parameters — single source of truth for both the
# in-process generators below and the streaming variants (io/skagen.py).
RMAT_DEFAULT_ABC = (0.57, 0.19, 0.19)


def rgg2d_radius(n: int, avg_degree: float) -> float:
    """Connection radius giving ~avg_degree expected neighbors on the
    unit square."""
    return float(np.sqrt(avg_degree / (np.pi * max(n, 1))))


def rgg3d_radius(n: int, avg_degree: float) -> float:
    """Connection radius giving ~avg_degree expected neighbors in the
    unit cube."""
    return float((avg_degree / (4.0 / 3.0 * np.pi * max(n, 1))) ** (1.0 / 3.0))


def make_delaunay(n: int, seed: Optional[int] = None) -> HostGraph:
    """Delaunay triangulation of n uniform random points on the unit
    square (the KaGen RDG2D analog) — the real-topology graph class the
    reference's quality claims are evaluated on (Walshaw/KaGen meshes)."""
    from scipy.spatial import Delaunay  # baked into the image

    rng = np.random.default_rng(seed if seed is not None else rng_mod.get_seed())
    pts = rng.random((n, 2))
    tri = Delaunay(pts)
    s = tri.simplices
    e = np.concatenate([s[:, [0, 1]], s[:, [1, 2]], s[:, [0, 2]]])
    e = np.unique(np.sort(e, axis=1), axis=0)
    return from_edge_list(n, e.astype(np.int64))


def make_fe_grid(rows: int, cols: int) -> HostGraph:
    """Triangulated structured grid: each unit cell split into two
    triangles, so interior nodes have degree 6 — an fe_ocean-class
    finite-element mesh stand-in (planar, bounded degree, small
    separators) built deterministically without external mesh files."""
    ids = np.arange(rows * cols, dtype=np.int64).reshape(rows, cols)
    right = np.stack([ids[:, :-1].ravel(), ids[:, 1:].ravel()], axis=1)
    down = np.stack([ids[:-1, :].ravel(), ids[1:, :].ravel()], axis=1)
    diag = np.stack([ids[:-1, :-1].ravel(), ids[1:, 1:].ravel()], axis=1)
    return from_edge_list(rows * cols, np.concatenate([right, down, diag]))


def make_rgg2d(
    n: int, avg_degree: float = 8.0, seed: Optional[int] = None
) -> HostGraph:
    """Random geometric graph on the unit square — the reference ships
    misc/rgg2d.metis as its sample workload; this generates comparable
    inputs of arbitrary size (stand-in for KaGen RGG2D)."""
    rng = np.random.default_rng(seed if seed is not None else rng_mod.get_seed())
    pts = rng.random((n, 2))
    radius = rgg2d_radius(n, avg_degree)
    # cell-grid neighbor search
    ncell = max(1, int(1.0 / radius))
    cell = (pts * ncell).astype(np.int64).clip(0, ncell - 1)
    cell_id = cell[:, 0] * ncell + cell[:, 1]
    order = np.argsort(cell_id, kind="stable")
    edges = []
    starts = np.searchsorted(cell_id[order], np.arange(ncell * ncell + 1))
    for dx in (-1, 0, 1):
        for dy in (-1, 0, 1):
            # compare each cell against neighbor cell (dx, dy)
            for cx in range(ncell):
                nx = cx + dx
                if not (0 <= nx < ncell):
                    continue
                for cy in range(ncell):
                    ny = cy + dy
                    if not (0 <= ny < ncell):
                        continue
                    a = order[starts[cx * ncell + cy] : starts[cx * ncell + cy + 1]]
                    b = order[starts[nx * ncell + ny] : starts[nx * ncell + ny + 1]]
                    if len(a) == 0 or len(b) == 0:
                        continue
                    d2 = ((pts[a, None, :] - pts[None, b, :]) ** 2).sum(-1)
                    ii, jj = np.nonzero(d2 <= radius * radius)
                    mask = a[ii] < b[jj]
                    if mask.any():
                        edges.append(np.stack([a[ii][mask], b[jj][mask]], axis=1))
    all_edges = (
        np.concatenate(edges) if edges else np.zeros((0, 2), dtype=np.int64)
    )
    return from_edge_list(n, all_edges)


def make_rmat(
    n: int,
    m: int,
    a: float = RMAT_DEFAULT_ABC[0],
    b: float = RMAT_DEFAULT_ABC[1],
    c: float = RMAT_DEFAULT_ABC[2],
    seed: Optional[int] = None,
) -> HostGraph:
    """RMAT generator (stand-in for KaGen RMAT; BASELINE.json's scale-22
    workload).  n must be a power of two."""
    rng = np.random.default_rng(seed if seed is not None else rng_mod.get_seed())
    scale = int(np.log2(n))
    if 1 << scale != n:
        raise ValueError("rmat n must be a power of two")
    probs = np.array([a, b, c, 1.0 - a - b - c])
    src = np.zeros(m, dtype=np.int64)
    dst = np.zeros(m, dtype=np.int64)
    for bit in range(scale):
        quad = rng.choice(4, size=m, p=probs)
        src = (src << 1) | (quad >> 1)
        dst = (dst << 1) | (quad & 1)
    return from_edge_list(n, np.stack([src, dst], axis=1))


def make_rgg3d(
    n: int, avg_degree: float = 8.0, seed: Optional[int] = None
) -> HostGraph:
    """Random geometric graph on the unit cube (KaGen RGG3D stand-in,
    kaminpar-io/dist_skagen.cc generator lineage)."""
    rng = np.random.default_rng(seed if seed is not None else rng_mod.get_seed())
    pts = rng.random((n, 3))
    radius = (avg_degree * 3.0 / (4.0 * np.pi * n)) ** (1.0 / 3.0)
    ncell = max(1, int(1.0 / radius))
    cell = (pts * ncell).astype(np.int64).clip(0, ncell - 1)
    cell_id = (cell[:, 0] * ncell + cell[:, 1]) * ncell + cell[:, 2]
    order = np.argsort(cell_id, kind="stable")
    starts = np.searchsorted(cell_id[order], np.arange(ncell**3 + 1))
    edges = []
    r2 = radius * radius
    for cid in range(ncell**3):
        a = order[starts[cid] : starts[cid + 1]]
        if len(a) == 0:
            continue
        cx, rem = divmod(cid, ncell * ncell)
        cy, cz = divmod(rem, ncell)
        for dx in (-1, 0, 1):
            nx = cx + dx
            if not (0 <= nx < ncell):
                continue
            for dy in (-1, 0, 1):
                ny = cy + dy
                if not (0 <= ny < ncell):
                    continue
                for dz in (-1, 0, 1):
                    nz = cz + dz
                    if not (0 <= nz < ncell):
                        continue
                    nid = (nx * ncell + ny) * ncell + nz
                    b = order[starts[nid] : starts[nid + 1]]
                    if len(b) == 0:
                        continue
                    d2 = ((pts[a, None, :] - pts[None, b, :]) ** 2).sum(-1)
                    ii, jj = np.nonzero(d2 <= r2)
                    mask = a[ii] < b[jj]
                    if mask.any():
                        edges.append(
                            np.stack([a[ii][mask], b[jj][mask]], axis=1)
                        )
    all_edges = (
        np.concatenate(edges) if edges else np.zeros((0, 2), dtype=np.int64)
    )
    return from_edge_list(n, all_edges)


def make_gnm(n: int, m: int, seed: Optional[int] = None) -> HostGraph:
    """Uniform random graph with ~m undirected edges (KaGen GNM_UNDIRECTED
    stand-in)."""
    rng = np.random.default_rng(seed if seed is not None else rng_mod.get_seed())
    src = rng.integers(0, n, m, dtype=np.int64)
    dst = rng.integers(0, n, m, dtype=np.int64)
    keep = src != dst
    return from_edge_list(n, np.stack([src[keep], dst[keep]], axis=1))


def make_ba(n: int, d: int = 4, seed: Optional[int] = None) -> HostGraph:
    """Barabási–Albert preferential attachment (KaGen BA stand-in): each
    new node attaches to d targets sampled from the current edge list
    (the classic repeated-endpoint trick)."""
    rng = np.random.default_rng(seed if seed is not None else rng_mod.get_seed())
    targets = np.zeros(2 * n * d, dtype=np.int64)
    edges = np.empty((n * d, 2), dtype=np.int64)
    cnt = 0
    for u in range(n):
        for j in range(d):
            if cnt == 0 or rng.random() < 0.5 or u == 0:
                t = int(rng.integers(0, max(u, 1)))
            else:
                t = int(targets[int(rng.integers(0, 2 * cnt))])
            edges[cnt] = (u, t)
            targets[2 * cnt] = u
            targets[2 * cnt + 1] = t
            cnt += 1
    e = edges[:cnt]
    e = e[e[:, 0] != e[:, 1]]
    return from_edge_list(n, e)


def make_grid3d(x: int, y: int, z: int) -> HostGraph:
    """3D grid graph (KaGen GRID_3D stand-in)."""
    idx = np.arange(x * y * z).reshape(x, y, z)
    edges = []
    edges.append(np.stack([idx[:-1].ravel(), idx[1:].ravel()], axis=1))
    edges.append(
        np.stack([idx[:, :-1].ravel(), idx[:, 1:].ravel()], axis=1)
    )
    edges.append(
        np.stack([idx[:, :, :-1].ravel(), idx[:, :, 1:].ravel()], axis=1)
    )
    return from_edge_list(x * y * z, np.concatenate(edges))


_GENERATORS = {
    "rgg2d": make_rgg2d,
    "rgg3d": make_rgg3d,
    "rmat": make_rmat,
    "gnm": make_gnm,
    "ba": make_ba,
    "grid2d": lambda rows, cols: make_grid_graph(rows, cols),
    "grid3d": make_grid3d,
    "delaunay": make_delaunay,
    "fegrid": make_fe_grid,
}


def parse_gen_spec(spec: str) -> tuple:
    """Parse a KaGen-style option string (dKaMinPar's
    `-G "<type>;<key>=<value>;..."` surface, kaminpar-io/dist_skagen.h)
    into (name, kwargs) — shared by the in-process and streaming
    (io/skagen.py) generator paths."""
    parts = [p for p in spec.replace("gen:", "", 1).split(";") if p]
    name = parts[0]
    kwargs = {}
    for p in parts[1:]:
        key, _, value = p.partition("=")
        kwargs[key.strip()] = float(value) if "." in value else int(value)
    return name, kwargs


def generate(spec: str) -> HostGraph:
    """Build a synthetic graph from a KaGen-style option string: e.g.
    "rgg2d;n=1024;avg_degree=8", "rmat;n=65536;m=1000000;seed=1",
    "grid3d;x=8;y=8;z=8"."""
    name, kwargs = parse_gen_spec(spec)
    if name not in _GENERATORS:
        raise ValueError(
            f"unknown generator '{name}' (available: {sorted(_GENERATORS)})"
        )
    return _GENERATORS[name](**kwargs)
