"""Host-side (numpy) CSR graph.

TPU-native analog of kaminpar-shm/datastructures/csr_graph.h:35 — the
`nodes[n+1] / edges[m] / node_weights / edge_weights` StaticArray quartet —
kept as numpy arrays on the host.  The host graph is the ingestion / IO /
initial-partitioning representation; `kaminpar_tpu.graphs.csr.DeviceGraph`
is its padded device twin.

Also hosts the graph utilities that the reference keeps in
kaminpar-shm/graphutils/: degree-bucket permutation (permutator.h:233),
validation (graph_validator.cc), and block-induced subgraph extraction
(subgraph_extractor.h:36).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

NODE_DTYPE = np.int32
WEIGHT_DTYPE = np.int64


@dataclass
class HostGraph:
    """CSR graph on the host. Undirected graphs store each edge twice
    (METIS convention), exactly like the reference's CSRGraph."""

    xadj: np.ndarray  # int (n+1,) row pointers
    adjncy: np.ndarray  # int32 (m,) neighbor ids
    node_weights: Optional[np.ndarray] = None  # int (n,) or None => unit
    edge_weights: Optional[np.ndarray] = None  # int (m,) or None => unit

    def __post_init__(self) -> None:
        self.xadj = np.asarray(self.xadj, dtype=np.int64)
        self.adjncy = np.asarray(self.adjncy, dtype=NODE_DTYPE)
        if self.node_weights is not None:
            self.node_weights = np.asarray(self.node_weights, dtype=WEIGHT_DTYPE)
        if self.edge_weights is not None:
            self.edge_weights = np.asarray(self.edge_weights, dtype=WEIGHT_DTYPE)

    # -- basic properties (CSRGraph interface surface, csr_graph.h) --
    @property
    def n(self) -> int:
        return len(self.xadj) - 1

    @property
    def m(self) -> int:
        return len(self.adjncy)

    def is_node_weighted(self) -> bool:
        return self.node_weights is not None

    def is_edge_weighted(self) -> bool:
        return self.edge_weights is not None

    def node_weight_array(self) -> np.ndarray:
        if self.node_weights is None:
            return np.ones(self.n, dtype=WEIGHT_DTYPE)
        return self.node_weights

    def edge_weight_array(self) -> np.ndarray:
        if self.edge_weights is None:
            return np.ones(self.m, dtype=WEIGHT_DTYPE)
        return self.edge_weights

    @property
    def total_node_weight(self) -> int:
        return self.n if self.node_weights is None else int(self.node_weights.sum())

    @property
    def total_edge_weight(self) -> int:
        return self.m if self.edge_weights is None else int(self.edge_weights.sum())

    def degrees(self) -> np.ndarray:
        return (self.xadj[1:] - self.xadj[:-1]).astype(np.int64)

    def max_degree(self) -> int:
        return 0 if self.n == 0 else int(self.degrees().max())

    def neighbors(self, u: int) -> np.ndarray:
        return self.adjncy[self.xadj[u] : self.xadj[u + 1]]

    def edge_sources(self) -> np.ndarray:
        """COO source per directed edge (repeat-interleave of node ids)."""
        return np.repeat(
            np.arange(self.n, dtype=NODE_DTYPE), self.degrees()
        )


def from_edge_list(
    n: int,
    edges: np.ndarray,
    edge_weights: Optional[np.ndarray] = None,
    node_weights: Optional[np.ndarray] = None,
    symmetrize: bool = True,
) -> HostGraph:
    """Build a CSR HostGraph from an (e, 2) array of undirected edges.

    Each undirected edge is materialized in both directions (METIS/CSRGraph
    convention).  Parallel edges are merged by weight sum; self-loops dropped.
    """
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if edge_weights is None:
        edge_weights = np.ones(len(edges), dtype=WEIGHT_DTYPE)
    edge_weights = np.asarray(edge_weights, dtype=WEIGHT_DTYPE)

    if symmetrize:
        src = np.concatenate([edges[:, 0], edges[:, 1]])
        dst = np.concatenate([edges[:, 1], edges[:, 0]])
        w = np.concatenate([edge_weights, edge_weights])
    else:
        src, dst, w = edges[:, 0], edges[:, 1], edge_weights

    keep = src != dst
    src, dst, w = src[keep], dst[keep], w[keep]

    # merge duplicates
    key = src * n + dst
    order = np.argsort(key, kind="stable")
    key, src, dst, w = key[order], src[order], dst[order], w[order]
    if len(key):
        uniq_mask = np.empty(len(key), dtype=bool)
        uniq_mask[0] = True
        uniq_mask[1:] = key[1:] != key[:-1]
        seg = np.cumsum(uniq_mask) - 1
        w = np.bincount(seg, weights=w, minlength=seg[-1] + 1 if len(seg) else 0).astype(
            WEIGHT_DTYPE
        )
        src, dst = src[uniq_mask], dst[uniq_mask]

    xadj = np.zeros(n + 1, dtype=np.int64)
    np.add.at(xadj, src + 1, 1)
    xadj = np.cumsum(xadj)
    unit_w = bool(len(w) == 0 or (w == 1).all())
    return HostGraph(
        xadj=xadj,
        adjncy=dst.astype(NODE_DTYPE),
        node_weights=node_weights,
        edge_weights=None if unit_w else w,
    )


def from_csr(
    xadj, adjncy, node_weights=None, edge_weights=None
) -> HostGraph:
    return HostGraph(xadj, adjncy, node_weights, edge_weights)


# ---------------------------------------------------------------------------
# Validation (analog of kaminpar-shm/graphutils/graph_validator.cc)
# ---------------------------------------------------------------------------


def validate(graph: HostGraph, undirected: bool = True) -> None:
    """Raise ValueError on malformed CSR; checks the same invariants as the
    reference validator: monotone xadj, in-range neighbors, no self-loops,
    and (optionally) symmetry with matching edge weights."""
    n, m = graph.n, graph.m
    if graph.xadj[0] != 0 or graph.xadj[-1] != m:
        raise ValueError("xadj must start at 0 and end at m")
    if (np.diff(graph.xadj) < 0).any():
        raise ValueError("xadj must be non-decreasing")
    if m and (graph.adjncy.min() < 0 or graph.adjncy.max() >= n):
        raise ValueError("neighbor id out of range")
    src = graph.edge_sources()
    if (src == graph.adjncy).any():
        raise ValueError("self loops are not allowed")
    if undirected and m:
        w = graph.edge_weight_array()
        fwd = np.lexsort((graph.adjncy, src))
        rev = np.lexsort((src, graph.adjncy))
        if not (
            np.array_equal(src[fwd], graph.adjncy[rev])
            and np.array_equal(graph.adjncy[fwd], src[rev])
            and np.array_equal(w[fwd], w[rev])
        ):
            raise ValueError("graph is not symmetric (or edge weights differ)")


# ---------------------------------------------------------------------------
# Permutation / degree buckets (analog of graphutils/permutator.{h,cc})
# ---------------------------------------------------------------------------


@dataclass
class NodePermutation:
    old_to_new: np.ndarray
    new_to_old: np.ndarray


def degree_bucket_permutation(graph: HostGraph) -> NodePermutation:
    """Stable sort of nodes into exponentially-spaced degree buckets
    (permutator.h:233 rearrange_by_degree_buckets).  Bucket of a node is
    floor(log2(degree))+1, bucket 0 = isolated nodes — keeping low-degree
    nodes contiguous is what lets the device kernels use shape-bucketed
    batches for skewed degree distributions."""
    deg = graph.degrees()
    bucket = np.zeros(graph.n, dtype=np.int64)
    nz = deg > 0
    bucket[nz] = np.floor(np.log2(deg[nz])).astype(np.int64) + 1
    new_to_old = np.argsort(bucket, kind="stable").astype(NODE_DTYPE)
    old_to_new = np.empty_like(new_to_old)
    old_to_new[new_to_old] = np.arange(graph.n, dtype=NODE_DTYPE)
    return NodePermutation(old_to_new=old_to_new, new_to_old=new_to_old)


def apply_permutation(graph: HostGraph, perm: NodePermutation) -> HostGraph:
    """Rebuild the CSR with nodes renumbered by perm.old_to_new."""
    deg = graph.degrees()
    new_deg = deg[perm.new_to_old]
    new_xadj = np.zeros(graph.n + 1, dtype=np.int64)
    np.cumsum(new_deg, out=new_xadj[1:])
    new_ew = None if graph.edge_weights is None else np.empty_like(graph.edge_weights)
    # vectorized edge copy: for each new node u, its edge range maps to the
    # old node's range
    old_starts = graph.xadj[perm.new_to_old]
    idx = np.repeat(old_starts, new_deg) + (
        np.arange(graph.m) - np.repeat(new_xadj[:-1], new_deg)
    )
    new_adjncy = perm.old_to_new[graph.adjncy[idx]]
    if new_ew is not None:
        new_ew = graph.edge_weights[idx]
    nw = None
    if graph.node_weights is not None:
        nw = graph.node_weights[perm.new_to_old]
    return HostGraph(new_xadj, new_adjncy.astype(NODE_DTYPE), nw, new_ew)


def count_isolated_nodes(graph: HostGraph) -> int:
    return int((graph.degrees() == 0).sum())


def remove_isolated_nodes(
    graph: HostGraph,
) -> Tuple[HostGraph, NodePermutation, int]:
    """Push isolated nodes to the back and return the subgraph without them
    (kaminpar.cc:392-404).  Returns (core graph, permutation over the FULL
    node set, num_isolated)."""
    deg = graph.degrees()
    isolated = deg == 0
    num_isolated = int(isolated.sum())
    new_to_old = np.concatenate(
        [np.flatnonzero(~isolated), np.flatnonzero(isolated)]
    ).astype(NODE_DTYPE)
    old_to_new = np.empty_like(new_to_old)
    old_to_new[new_to_old] = np.arange(graph.n, dtype=NODE_DTYPE)
    perm = NodePermutation(old_to_new=old_to_new, new_to_old=new_to_old)
    permuted = apply_permutation(graph, perm)
    core_n = graph.n - num_isolated
    core = HostGraph(
        xadj=permuted.xadj[: core_n + 1],
        adjncy=permuted.adjncy,
        node_weights=None
        if permuted.node_weights is None
        else permuted.node_weights[:core_n],
        edge_weights=permuted.edge_weights,
    )
    return core, perm, num_isolated


# ---------------------------------------------------------------------------
# Subgraph extraction (analog of graphutils/subgraph_extractor.{h,cc})
# ---------------------------------------------------------------------------


@dataclass
class SubgraphExtraction:
    subgraphs: list  # list[HostGraph], one per block
    node_mapping: np.ndarray  # (n,) position of node inside its block subgraph


def extract_block_subgraphs(
    graph: HostGraph, partition: np.ndarray, k: int
) -> SubgraphExtraction:
    """Extract the k block-induced subgraphs (subgraph_extractor.h:103-177).
    Edges crossing blocks are dropped; node ids are renumbered per block."""
    partition = np.asarray(partition)
    order = np.argsort(partition, kind="stable").astype(NODE_DTYPE)
    # position of each node within its block
    block_sizes = np.bincount(partition, minlength=k)
    block_starts = np.concatenate([[0], np.cumsum(block_sizes)])
    pos_in_block = np.empty(graph.n, dtype=NODE_DTYPE)
    pos_in_block[order] = (
        np.arange(graph.n, dtype=NODE_DTYPE) - block_starts[partition[order]]
    ).astype(NODE_DTYPE)

    src = graph.edge_sources()
    ew = graph.edge_weight_array()
    nw = graph.node_weight_array()

    subgraphs = []
    for b in range(k):
        nodes_b = order[block_starts[b] : block_starts[b + 1]]
        edge_mask = (partition[src] == b) & (partition[graph.adjncy] == b)
        s = pos_in_block[src[edge_mask]]
        d = pos_in_block[graph.adjncy[edge_mask]]
        w = ew[edge_mask]
        nb = len(nodes_b)
        xadj = np.zeros(nb + 1, dtype=np.int64)
        np.add.at(xadj, s + 1, 1)
        xadj = np.cumsum(xadj)
        o = np.lexsort((d, s))
        sub = HostGraph(
            xadj=xadj,
            adjncy=d[o].astype(NODE_DTYPE),
            node_weights=nw[nodes_b] if graph.node_weights is not None else None,
            edge_weights=w[o] if graph.edge_weights is not None else None,
        )
        subgraphs.append(sub)
    return SubgraphExtraction(subgraphs=subgraphs, node_mapping=pos_in_block)


# ---------------------------------------------------------------------------
# Host contraction (numpy twin of ops/contraction.contract_clustering; used
# by the distributed driver where the coarse graph is rebuilt host-side
# before redistribution, and by the sequential initial-partitioning path)
# ---------------------------------------------------------------------------


def host_partition_metrics(graph: HostGraph, partition, k: int) -> dict:
    """Cut / block weights / imbalance / feasibility on the host (the
    numpy twin of ops/metrics; shared by the RESULT printer and the
    partition-properties tool so the definitions cannot drift)."""
    partition = np.asarray(partition)
    src = graph.edge_sources()
    ew = graph.edge_weight_array()
    cut = int(ew[partition[src] != partition[graph.adjncy]].sum() // 2)
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, partition, graph.node_weight_array())
    perfect = max(1, -(-graph.total_node_weight // max(k, 1)))
    return {
        "cut": cut,
        "block_weights": bw,
        "imbalance": bw.max() / perfect - 1.0 if k else 0.0,
    }


def contract_clustering_host(
    graph: HostGraph, labels: np.ndarray
) -> tuple[HostGraph, np.ndarray]:
    """Contract a clustering on the host.

    `labels[i]` is node i's cluster (any values); returns (coarse graph,
    cmap) where cmap densely remaps fine node -> coarse node, coarse node
    weights are cluster sums, and coarse edges aggregate inter-cluster
    weights (self-loops dropped) — the same semantics as the reference's
    contract_clustering (kaminpar-shm/coarsening/contraction/
    cluster_contraction.h:50-59).
    """
    labels = np.asarray(labels)[: graph.n]
    uniq, cmap = np.unique(labels, return_inverse=True)
    c_n = len(uniq)
    cmap = cmap.astype(np.int32)

    c_node_w = np.zeros(c_n, dtype=np.int64)
    np.add.at(c_node_w, cmap, graph.node_weight_array())

    src = cmap[graph.edge_sources()]
    dst = cmap[graph.adjncy]
    w = graph.edge_weight_array()
    keep = src != dst
    coarse = from_edge_list(
        c_n,
        np.stack([src[keep], dst[keep]], axis=1),
        edge_weights=w[keep],
        node_weights=c_node_w,
        symmetrize=False,
    )
    return coarse, cmap
