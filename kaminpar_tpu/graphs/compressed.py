"""Compressed host graph (TeraPart analog).

The reference's memory-frugal mode stores neighborhoods gap+varint encoded
(kaminpar-common/graph_compression/compressed_neighborhoods.h:52-60,
varint.h; datastructures/compressed_graph.h:30) so tera-scale graphs fit in
RAM.  In the TPU framework the *device* graph must stay flat int32 CSR (XLA
kernels want dense arrays), so compression lives on the host side of the
DLPack boundary: a `CompressedHostGraph` holds the varint-gap streams
(encoded/decoded by the native C++ codec, kaminpar_tpu/native/codec.cpp)
and materializes plain CSR lazily — whole-graph for device upload, per-node
for host algorithms.

Edge weights, when present, are stored as raw arrays (the reference
interleaves varint-coded weights; a follow-up can pack them the same way —
unweighted graphs, the common tera-scale case, already get the full
benefit).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import native
from .host import HostGraph


@dataclass
class CompressedHostGraph:
    """Varint-gap compressed CSR (CompressedGraph analog)."""

    xadj: np.ndarray  # i64[n+1] degrees prefix (uncompressed, like reference)
    offsets: np.ndarray  # i64[n+1] byte offset per node's stream
    data: np.ndarray  # u8[total] varint gap streams
    node_weights: Optional[np.ndarray] = None
    edge_weights: Optional[np.ndarray] = None

    @property
    def n(self) -> int:
        return len(self.xadj) - 1

    @property
    def m(self) -> int:
        return int(self.xadj[-1])

    def degrees(self) -> np.ndarray:
        return self.xadj[1:] - self.xadj[:-1]

    def neighbors(self, u: int) -> np.ndarray:
        """Decode one node (compressed_graph.h adjacent_nodes analog)."""
        return native.decode_node(u, self.xadj, self.offsets, self.data)

    def decode(self) -> HostGraph:
        """Materialize the full CSR graph."""
        adjncy = native.decode_gaps(self.xadj, self.offsets, self.data)
        return HostGraph(
            xadj=self.xadj.copy(),
            adjncy=adjncy,
            node_weights=self.node_weights,
            edge_weights=self.edge_weights,
        )

    def node_weight_array(self) -> np.ndarray:
        if self.node_weights is not None:
            return np.asarray(self.node_weights, dtype=np.int64)
        return np.ones(self.n, dtype=np.int64)

    @property
    def total_node_weight(self) -> int:
        return int(self.node_weight_array().sum())

    def memory_bytes(self) -> int:
        total = self.xadj.nbytes + self.offsets.nbytes + self.data.nbytes
        if self.node_weights is not None:
            total += self.node_weights.nbytes
        if self.edge_weights is not None:
            total += self.edge_weights.nbytes
        return total

    def compression_ratio(self) -> float:
        """Uncompressed adjacency bytes / compressed stream bytes
        (the reference reports the same ratio in its compression stats)."""
        raw = self.m * 4
        return raw / max(1, self.data.nbytes)


def compress_host_graph(graph: HostGraph) -> CompressedHostGraph:
    """Build the compressed form (compressed_graph_builder.h analog).

    Neighborhoods must be sorted ascending for gap coding; the builder
    sorts per node when needed (the reference's builder requires the same
    and offers reorder_edges_by_compression, permutator.h:241)."""
    adjncy = graph.adjncy
    xadj = np.asarray(graph.xadj, dtype=np.int64)
    # ensure sorted neighborhoods (cheap check first)
    needs_sort = False
    if graph.m:
        d = np.diff(adjncy.astype(np.int64))
        row_start = np.zeros(graph.m, dtype=bool)
        row_start[xadj[:-1][graph.degrees() > 0]] = True
        needs_sort = bool((d < 0)[~row_start[1:]].any())
    ew = graph.edge_weights
    if needs_sort:
        src = graph.edge_sources()
        order = np.lexsort((adjncy, src))
        adjncy = adjncy[order]
        if ew is not None:
            ew = np.asarray(ew)[order]
    data, offsets = native.encode_gaps(xadj, adjncy)
    return CompressedHostGraph(
        xadj=xadj,
        offsets=offsets,
        data=data,
        node_weights=graph.node_weights,
        edge_weights=ew,
    )
