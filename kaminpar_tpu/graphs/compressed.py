"""Compressed host graph (TeraPart analog).

The reference's memory-frugal mode stores neighborhoods gap+varint encoded
with interval encoding for consecutive runs and a StreamVByte batch codec
(kaminpar-common/graph_compression/compressed_neighborhoods.h:52-60,
varint.h, streamvbyte.h; datastructures/compressed_graph.h:30) so
tera-scale graphs fit in RAM.  In the TPU framework the *device* graph
must stay flat int32 CSR (XLA kernels want dense arrays), so compression
lives on the host side of the DLPack boundary: a `CompressedHostGraph`
holds the encoded streams (native C++ codecs, kaminpar_tpu/native/
codec.cpp + codec2.cpp) and materializes plain CSR lazily — whole-graph
for device upload, per-node for host algorithms.

Two codecs:
  * "gap"  — varint gap streams (codec.cpp; numpy fallback exists);
  * "v2"   — interval encoding + StreamVByte-class packed residuals +
             varint edge weights (codec2.cpp; native only) — the
             TeraPart-parity codec and the default when the native
             library is available.  Edge weights are stored COMPRESSED
             in the v2 emit order (interval members first), so decoded
             adjacency and weights always pair 1:1.

The reference's high-degree split (compressed_neighborhoods.h) exists to
parallelize per-node decode across threads; bulk decode here is one
native pass, so degree skew needs no special casing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import native
from .host import HostGraph


@dataclass
class CompressedHostGraph:
    """Compressed CSR (CompressedGraph analog)."""

    xadj: np.ndarray  # i64[n+1] degrees prefix (uncompressed, like reference)
    offsets: np.ndarray  # i64[n+1] byte offset per node's stream
    data: np.ndarray  # u8[total] encoded neighborhoods
    node_weights: Optional[np.ndarray] = None
    edge_weights: Optional[np.ndarray] = None  # raw (gap codec only)
    codec: str = "gap"  # "gap" (codec.cpp) or "v2" (codec2.cpp)
    wdata: Optional[np.ndarray] = None  # u8: varint weights (v2 only)
    woffsets: Optional[np.ndarray] = None  # i64[n+1] (v2 only)

    def __post_init__(self):
        if (
            self.codec == "v2"
            and self.edge_weights is not None
            and self.wdata is None
        ):
            # v2 decodes adjacency in EMIT order (interval members first),
            # so weights must come from the v2 weight stream (wdata),
            # which is written in the same order; a raw input-order
            # edge_weights array would silently misalign
            raise ValueError(
                "v2-codec graphs must carry edge weights as wdata "
                "(emit-order compressed stream), not raw edge_weights"
            )

    @property
    def n(self) -> int:
        return len(self.xadj) - 1

    @property
    def m(self) -> int:
        return int(self.xadj[-1])

    def degrees(self) -> np.ndarray:
        return self.xadj[1:] - self.xadj[:-1]

    def neighbors(self, u: int) -> np.ndarray:
        """Decode one node (compressed_graph.h adjacent_nodes analog)."""
        if self.codec == "v2":
            return native.decode_v2_node(u, self.xadj, self.offsets, self.data)
        return native.decode_node(u, self.xadj, self.offsets, self.data)

    def decode_range(self, v0: int, v1: int):
        """Decode the rows of node range [v0, v1) only — the decoders
        index their byte streams through absolute per-node offsets, so a
        slice of (rebased xadj, offsets) decodes independently.  Returns
        (xadj_rel i64[v1-v0+1], adjncy, edge_weights|None); peak memory
        is the range's plain rows, which is what lets the sharded
        ingestion path (parallel.dist_graph_from_compressed, the
        DistributedCompressedGraph analog) stream shards."""
        if not (0 <= v0 <= v1 <= self.n):
            raise IndexError((v0, v1))
        xadj_rel = self.xadj[v0 : v1 + 1] - self.xadj[v0]
        offs = self.offsets[v0 : v1 + 1]
        if self.codec == "v2":
            adjncy = native.decode_v2(xadj_rel, offs, self.data)
            # __post_init__ guarantees v2 never carries raw edge_weights
            ew = None
            if self.wdata is not None:
                ew = native.decode_v2_weights(
                    xadj_rel, self.woffsets[v0 : v1 + 1], self.wdata
                )
        else:
            adjncy = native.decode_gaps(xadj_rel, offs, self.data)
            ew = (
                None
                if self.edge_weights is None
                else self.edge_weights[self.xadj[v0] : self.xadj[v1]]
            )
        return xadj_rel, adjncy, ew

    def decode(self) -> HostGraph:
        """Materialize the full CSR graph."""
        if self.codec == "v2":
            adjncy = native.decode_v2(self.xadj, self.offsets, self.data)
            ew = self.edge_weights
            if self.wdata is not None:
                ew = native.decode_v2_weights(
                    self.xadj, self.woffsets, self.wdata
                )
        else:
            adjncy = native.decode_gaps(self.xadj, self.offsets, self.data)
            ew = self.edge_weights
        return HostGraph(
            xadj=self.xadj.copy(),
            adjncy=adjncy,
            node_weights=self.node_weights,
            edge_weights=ew,
        )

    def node_weight_array(self) -> np.ndarray:
        if self.node_weights is not None:
            return np.asarray(self.node_weights, dtype=np.int64)
        return np.ones(self.n, dtype=np.int64)

    @property
    def total_node_weight(self) -> int:
        return int(self.node_weight_array().sum())

    @property
    def total_edge_weight(self) -> int:
        """Sum of edge weights without decoding the adjacency (the
        weight stream alone is decoded when weights are compressed) —
        lets PartitionContext.setup run on a still-compressed graph."""
        if self.wdata is not None:
            w = native.decode_v2_weights(self.xadj, self.woffsets, self.wdata)
            return int(w.sum())
        if self.edge_weights is not None:
            return int(np.asarray(self.edge_weights, dtype=np.int64).sum())
        return self.m

    def memory_bytes(self) -> int:
        total = self.xadj.nbytes + self.offsets.nbytes + self.data.nbytes
        if self.node_weights is not None:
            total += self.node_weights.nbytes
        if self.edge_weights is not None:
            total += self.edge_weights.nbytes
        if self.wdata is not None:
            total += self.wdata.nbytes + self.woffsets.nbytes
        return total

    def compression_ratio(self) -> float:
        """Uncompressed adjacency(+weight) bytes / compressed stream bytes
        (the reference reports the same ratio in its compression stats)."""
        raw = self.m * 4
        enc = self.data.nbytes
        if self.wdata is not None:
            raw += self.m * 4
            enc += self.wdata.nbytes
        return raw / max(1, enc)


def compress_host_graph(
    graph: HostGraph, codec: str = "auto"
) -> CompressedHostGraph:
    """Build the compressed form (compressed_graph_builder.h analog).

    Neighborhoods must be sorted ascending for gap/interval coding; the
    builder sorts per node when needed (the reference's builder requires
    the same and offers reorder_edges_by_compression, permutator.h:241).
    `codec`: "v2" (TeraPart parity, native only), "gap", or "auto" (v2
    when the native library is available)."""
    adjncy = graph.adjncy
    xadj = np.asarray(graph.xadj, dtype=np.int64)
    # ensure sorted neighborhoods (cheap check first)
    needs_sort = False
    if graph.m:
        d = np.diff(adjncy.astype(np.int64))
        row_start = np.zeros(graph.m, dtype=bool)
        row_start[xadj[:-1][graph.degrees() > 0]] = True
        needs_sort = bool((d < 0)[~row_start[1:]].any())
    ew = graph.edge_weights
    if needs_sort:
        src = graph.edge_sources()
        order = np.lexsort((adjncy, src))
        adjncy = adjncy[order]
        if ew is not None:
            ew = np.asarray(ew)[order]
    if codec == "auto":
        codec = "v2" if native.available() else "gap"
    if codec == "v2":
        enc = native.encode_v2(xadj, adjncy)
        if enc is None:
            raise RuntimeError("v2 codec requires the native library")
        data, offsets = enc
        wdata = woffsets = None
        if ew is not None:
            wdata, woffsets = native.encode_v2_weights(xadj, adjncy, ew)
        return CompressedHostGraph(
            xadj=xadj,
            offsets=offsets,
            data=data,
            node_weights=graph.node_weights,
            edge_weights=None,
            codec="v2",
            wdata=wdata,
            woffsets=woffsets,
        )
    data, offsets = native.encode_gaps(xadj, adjncy)
    return CompressedHostGraph(
        xadj=xadj,
        offsets=offsets,
        data=data,
        node_weights=graph.node_weights,
        edge_weights=ew,
        codec="gap",
    )


def compressed_partition_metrics(
    cgraph: CompressedHostGraph,
    partition,
    k: int,
    chunk_nodes: int = 1 << 18,
) -> dict:
    """host_partition_metrics without decoding the full CSR: the cut is
    accumulated over decoded node-range chunks (decode_range), so peak
    host memory stays at compressed + one chunk + O(n).  Definitions
    match graphs.host.host_partition_metrics exactly (same RESULT line
    semantics)."""
    partition = np.asarray(partition)
    n = cgraph.n
    cut = 0
    for v0 in range(0, n, chunk_nodes):
        v1 = min(n, v0 + chunk_nodes)
        xr, adj, ew = cgraph.decode_range(v0, v1)
        deg = np.diff(np.asarray(xr, dtype=np.int64))
        src = np.repeat(np.arange(v0, v1, dtype=np.int64), deg)
        mask = partition[src] != partition[adj]
        cut += int(
            mask.sum() if ew is None else np.asarray(ew)[mask].sum()
        )
    nw = cgraph.node_weight_array()
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, partition, nw)
    perfect = max(1, -(-int(nw.sum()) // max(k, 1)))
    return {
        "cut": cut // 2,
        "block_weights": bw,
        "imbalance": bw.max() / perfect - 1.0 if k else 0.0,
    }


def compress_from_stream(sg, codec: str = "auto") -> CompressedHostGraph:
    """Compress a streamed graph (io/skagen.StreamedGraph) chunk by chunk
    — the full flat CSR never exists on the host (the reference's
    builder likewise ingests neighborhoods incrementally,
    compressed_graph_builder.h).  Peak memory: compressed streams + one
    decoded chunk + O(n).

    The per-node byte offsets of both codecs are absolute, so per-chunk
    encodings concatenate by rebasing each chunk's offsets by the bytes
    already written (decode_range depends on exactly this independence).
    """
    if codec == "auto":
        codec = "v2" if native.available() else "gap"
    n = sg.n
    xadj = np.zeros(n + 1, dtype=np.int64)
    data_parts, off_parts = [], []
    wdata_parts, woff_parts = [], []
    byte_base = 0
    wbyte_base = 0
    any_weights = False
    for ch in sg.chunks():
        xr = np.asarray(ch.xadj, dtype=np.int64)
        adj = np.asarray(ch.adjncy, dtype=np.int32)
        xadj[ch.v_begin + 1 : ch.v_end + 1] = xr[1:] - xr[:-1]
        if codec == "v2":
            enc = native.encode_v2(xr, adj)
            if enc is None:
                raise RuntimeError("v2 codec requires the native library")
            data_c, off_c = enc
        else:
            data_c, off_c = native.encode_gaps(xr, adj)
        data_parts.append(data_c)
        off_parts.append(np.asarray(off_c, dtype=np.int64)[:-1] + byte_base)
        byte_base += int(np.asarray(off_c)[-1])
        w = np.asarray(ch.adjwgt)
        if len(w) and not (w == 1).all():
            any_weights = True
        if codec == "v2":
            wd, wo = native.encode_v2_weights(xr, adj, w)
            wdata_parts.append(wd)
            woff_parts.append(
                np.asarray(wo, dtype=np.int64)[:-1] + wbyte_base
            )
            wbyte_base += int(np.asarray(wo)[-1])
        else:
            wdata_parts.append(w)
    np.cumsum(xadj, out=xadj)
    data = (
        np.concatenate(data_parts) if data_parts
        else np.zeros(0, dtype=np.uint8)
    )
    offsets = np.concatenate(
        (off_parts if off_parts else [np.zeros(0, np.int64)])
        + [np.asarray([byte_base], dtype=np.int64)]
    )
    if codec == "v2":
        if any_weights:
            wdata = np.concatenate(wdata_parts)
            woffsets = np.concatenate(
                woff_parts + [np.asarray([wbyte_base], dtype=np.int64)]
            )
        else:
            wdata = woffsets = None
        return CompressedHostGraph(
            xadj=xadj, offsets=offsets, data=data, codec="v2",
            wdata=wdata, woffsets=woffsets,
        )
    ew = np.concatenate(wdata_parts) if wdata_parts else None
    if ew is not None and (len(ew) == 0 or (ew == 1).all()):
        ew = None
    return CompressedHostGraph(
        xadj=xadj, offsets=offsets, data=data, codec="gap",
        edge_weights=ew,
    )


def extract_core_compressed(
    cgraph: CompressedHostGraph, chunk_nodes: int = 1 << 18
):
    """Compressed-to-compressed isolated-node extraction
    (kaminpar.cc:392-404 without ever materializing the flat CSR).

    Streams decoded node-range chunks, drops degree-0 rows, remaps
    neighbor ids through the monotone core numbering, re-sorts each row
    (v2 decodes in emit order; the encoders need ascending rows) and
    re-encodes — peak memory stays at compressed + one chunk + O(n).

    Returns (core CompressedHostGraph, core_ids, iso_ids): source node
    ids of the core (in order — the core numbering is their rank) and of
    the isolated nodes."""
    deg = cgraph.degrees()
    iso = deg == 0
    core_ids = np.flatnonzero(~iso)
    iso_ids = np.flatnonzero(iso)
    n_core = len(core_ids)
    new_id = (np.cumsum(~iso) - 1).astype(np.int64)

    class _CoreStream:
        n = n_core

        def chunks(self):
            from ..io.skagen import GraphChunk

            for v0 in range(0, cgraph.n, chunk_nodes):
                v1 = min(cgraph.n, v0 + chunk_nodes)
                keep = ~iso[v0:v1]
                if not keep.any():
                    continue
                xr, adj, ew = cgraph.decode_range(v0, v1)
                xr = np.asarray(xr, dtype=np.int64)
                dslice = np.diff(xr)
                adj2 = new_id[np.asarray(adj, dtype=np.int64)]
                row = np.repeat(np.arange(v1 - v0), dslice)
                order = np.lexsort((adj2, row))
                adj2 = adj2[order].astype(np.int32)
                w = (
                    np.ones(len(adj2), dtype=np.int64)
                    if ew is None
                    else np.asarray(ew, dtype=np.int64)[order]
                )
                # xadj of the kept rows only (isolated rows are empty, so
                # the edge stream is untouched by dropping them)
                kept_deg = dslice[keep]
                cxadj = np.concatenate(
                    [[0], np.cumsum(kept_deg)]
                ).astype(np.int64)
                first_core = int(new_id[v0 + int(np.argmax(keep))])
                yield GraphChunk(
                    v_begin=first_core,
                    v_end=first_core + int(keep.sum()),
                    xadj=cxadj,
                    adjncy=adj2,
                    adjwgt=w,
                )

    core = compress_from_stream(_CoreStream(), codec=cgraph.codec)
    if cgraph.node_weights is not None:
        core.node_weights = np.asarray(cgraph.node_weights)[core_ids]
    return core, core_ids, iso_ids
