"""Device-resident padded CSR graph (the TPU twin of CSRGraph).

Design (SURVEY.md §7 step 1): a pytree of device arrays with *padded, shape-
bucketed* sizes so the multilevel hierarchy (graph shrinks ~2x per level)
re-uses O(log n) compiled executables instead of recompiling per level.
Actual sizes `n`/`m` are traced int32 scalars; pad slots are inert:

  * node pad slots: weight 0, degree 0 (row_ptr clamped to m);
  * edge pad slots: src = dst = n_pad - 1 (a guaranteed-pad node), weight 0.

With that convention most kernels need no explicit masks — zero-weight edges
between pad nodes contribute nothing to ratings, cuts, or contractions.
The builder always pads n to at least n+1 so slot n_pad-1 is never a real
node.

Unlike the reference's lambda-based adjacency iteration
(kaminpar-shm/datastructures/csr_graph.h:171 adjacent_nodes), device kernels
work on the flat COO view (`src`, `dst` = col) — gather/segment programs are
the TPU-native idiom; XLA maps them onto vectorized scatter/sort units rather
than per-node loops.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..caching import pad_size
from .host import HostGraph

from ..dtypes import ACC_DTYPE, WEIGHT_DTYPE  # int64 under
# KAMINPAR_TPU_64BIT — see kaminpar_tpu/dtypes.py; ids stay int32 like
# the reference's default 32-bit ID build, CMakeLists.txt:67-75

NODE_DTYPE = jnp.int32


@jax.tree_util.register_dataclass
@dataclass
class DeviceGraph:
    """Padded CSR + COO graph on device.

    Fields (all jnp arrays):
      row_ptr : i32[n_pad + 1]  CSR offsets; row_ptr[i] = m for i >= n
      src     : i32[m_pad]      COO edge sources (pad: n_pad - 1)
      dst     : i32[m_pad]      COO edge targets == CSR adjncy (pad: n_pad - 1)
      edge_w  : i32[m_pad]      edge weights (pad: 0)
      node_w  : i32[n_pad]      node weights (pad: 0)
      n, m    : i32 scalars     true counts (traced, not static)
    """

    row_ptr: jax.Array
    src: jax.Array
    dst: jax.Array
    edge_w: jax.Array
    node_w: jax.Array
    n: jax.Array
    m: jax.Array

    @property
    def n_pad(self) -> int:
        return self.node_w.shape[0]

    @property
    def m_pad(self) -> int:
        return self.src.shape[0]

    @property
    def degrees(self) -> jax.Array:
        return self.row_ptr[1:] - self.row_ptr[:-1]

    def node_mask(self) -> jax.Array:
        return jnp.arange(self.n_pad, dtype=NODE_DTYPE) < self.n

    def edge_mask(self) -> jax.Array:
        return jnp.arange(self.m_pad, dtype=NODE_DTYPE) < self.m

    def total_node_weight(self) -> jax.Array:
        return jnp.sum(self.node_w.astype(ACC_DTYPE))

    def total_edge_weight(self) -> jax.Array:
        return jnp.sum(self.edge_w.astype(ACC_DTYPE))


def shape_floors() -> tuple[int, int]:
    """(n_floor, m_floor) shape-bucket floors for device graphs.

    On the remote TPU backend every distinct shape bucket costs a multi-
    minute XLA compile through the tunnel, and a limping coarsening tail
    (n shrinking ~10% per level) otherwise mints a fresh m_pad bucket per
    level — observed as 30-80 s of compiles for graphs of a few thousand
    nodes.  Padding every small level into ONE floor bucket trades ~0.2 s
    of extra warm work per call for ~a minute of compile per avoided
    bucket.  CPU (tests, fallback) keeps small floors so tiny unit-test
    graphs stay tiny."""
    from ..utils import platform

    try:
        backend = platform.default_backend()
    except Exception:
        backend = "cpu"
    if backend == "cpu":
        return 256, 256
    return 1 << 13, 1 << 20


def device_graph_from_host(
    graph: HostGraph,
    n_pad: Optional[int] = None,
    m_pad: Optional[int] = None,
    device=None,
) -> DeviceGraph:
    """Upload a HostGraph into the padded device layout."""
    # `device-oom` chaos injection point: an allocator-shaped failure at
    # upload propagates to the facade's recovery ladder
    # (resilience/memory.py), which retries at the next rung
    from ..resilience import maybe_inject

    maybe_inject("device-oom")
    from ..caching import record_padding

    n, m = graph.n, graph.m
    n_floor, m_floor = shape_floors()
    n_pad = n_pad if n_pad is not None else pad_size(n + 1, n_floor)
    m_pad = m_pad if m_pad is not None else pad_size(max(m, 1), m_floor)
    if n_pad < n + 1 or m_pad < m:
        raise ValueError("pad sizes too small")
    record_padding(n=n + 1, n_pad=n_pad, m=m, m_pad=m_pad)

    row_ptr = np.full(n_pad + 1, m, dtype=np.int32)
    row_ptr[: n + 1] = graph.xadj.astype(np.int32)

    pad_node = n_pad - 1
    src = np.full(m_pad, pad_node, dtype=np.int32)
    dst = np.full(m_pad, pad_node, dtype=np.int32)
    edge_w = np.zeros(m_pad, dtype=np.dtype(WEIGHT_DTYPE))
    src[:m] = graph.edge_sources()
    dst[:m] = graph.adjncy
    edge_w[:m] = graph.edge_weight_array().astype(np.dtype(WEIGHT_DTYPE))

    node_w = np.zeros(n_pad, dtype=np.dtype(WEIGHT_DTYPE))
    node_w[:n] = graph.node_weight_array().astype(np.dtype(WEIGHT_DTYPE))

    from ..caching import record_transfer

    record_transfer(
        "h2d",
        row_ptr.nbytes + src.nbytes + dst.nbytes + edge_w.nbytes
        + node_w.nbytes,
        kind="csr-upload",
    )
    put = partial(jax.device_put, device=device)
    return DeviceGraph(
        row_ptr=put(row_ptr),
        src=put(src),
        dst=put(dst),
        edge_w=put(edge_w),
        node_w=put(node_w),
        n=put(np.int32(n)),
        m=put(np.int32(m)),
    )


def device_graph_from_compressed(
    cgraph,
    n_pad: Optional[int] = None,
    m_pad: Optional[int] = None,
    chunk_nodes: int = 1 << 18,
) -> DeviceGraph:
    """Upload a CompressedHostGraph into the padded device layout WITHOUT
    ever materializing the full CSR on the host (TeraPart compute parity:
    the reference partitions directly from compressed neighborhoods,
    ref: kaminpar-common/graph_compression/compressed_neighborhoods.h:52-60
    + kaminpar-shm/datastructures/compressed_graph.h:30.  XLA kernels
    need flat device arrays, so "directly" on a TPU means the DECODE
    streams: node-range chunks are decoded (decode_range), uploaded, and
    concatenated ON DEVICE — peak host memory is the compressed streams
    + one chunk + O(n), never the flat edge list).

    The resulting DeviceGraph is bitwise identical to
    device_graph_from_host(cgraph.decode()), so downstream kernels and
    compile caches are untouched."""
    # `compressed-stream` degradation site: a failure here (device OOM
    # mid-stream, injected chaos fault) propagates to the facade's
    # with_fallback wrapper, which decodes to the plain host CSR and
    # re-partitions (kaminpar._partition_core_resilient)
    from ..resilience import maybe_inject

    maybe_inject("compressed-stream")
    n, m = cgraph.n, cgraph.m
    n_floor, m_floor = shape_floors()
    n_pad = n_pad if n_pad is not None else pad_size(n + 1, n_floor)
    m_pad = m_pad if m_pad is not None else pad_size(max(m, 1), m_floor)
    if n_pad < n + 1 or m_pad < m:
        raise ValueError("pad sizes too small")
    from ..caching import record_padding

    record_padding(n=n + 1, n_pad=n_pad, m=m, m_pad=m_pad)
    pad_node = n_pad - 1

    # O(n) arrays come straight from the (uncompressed) offsets
    xadj = np.asarray(cgraph.xadj, dtype=np.int64)
    row_ptr = np.full(n_pad + 1, m, dtype=np.int32)
    row_ptr[: n + 1] = xadj.astype(np.int32)
    node_w = np.zeros(n_pad, dtype=np.dtype(WEIGHT_DTYPE))
    node_w[:n] = cgraph.node_weight_array().astype(np.dtype(WEIGHT_DTYPE))

    src_parts, dst_parts, w_parts = [], [], []
    uploaded_bytes = row_ptr.nbytes + node_w.nbytes
    for v0 in range(0, n, chunk_nodes):
        v1 = min(n, v0 + chunk_nodes)
        xr, adj, ew = cgraph.decode_range(v0, v1)
        deg = np.diff(np.asarray(xr, dtype=np.int64))
        src_c = np.repeat(
            np.arange(v0, v1, dtype=np.int32), deg
        )
        uploaded_bytes += 2 * src_c.nbytes + (
            0 if ew is None
            else len(src_c) * np.dtype(WEIGHT_DTYPE).itemsize
        )
        src_parts.append(jax.device_put(src_c))
        dst_parts.append(jax.device_put(np.asarray(adj, dtype=np.int32)))
        if ew is None:
            w_parts.append(
                jnp.ones(len(src_c), dtype=np.dtype(WEIGHT_DTYPE))
            )
        else:
            w_parts.append(
                jax.device_put(
                    np.asarray(ew, dtype=np.dtype(WEIGHT_DTYPE))
                )
            )
        del xr, adj, ew, src_c  # keep the host high-water at one chunk

    def assemble(parts, fill, dtype):
        tail = jnp.full(m_pad - m, fill, dtype=dtype)
        return jnp.concatenate(list(parts) + [tail]) if m_pad > m else (
            jnp.concatenate(parts)
        )

    src = assemble(src_parts, pad_node, jnp.int32)
    dst = assemble(dst_parts, pad_node, jnp.int32)
    edge_w = assemble(w_parts, 0, np.dtype(WEIGHT_DTYPE))
    from ..caching import record_transfer

    record_transfer("h2d", uploaded_bytes, kind="csr-upload")
    return DeviceGraph(
        row_ptr=jax.device_put(row_ptr),
        src=src,
        dst=dst,
        edge_w=edge_w,
        node_w=jax.device_put(node_w),
        n=jax.device_put(np.int32(n)),
        m=jax.device_put(np.int32(m)),
    )


def host_graph_from_device(graph: DeviceGraph) -> HostGraph:
    """Download a DeviceGraph back into a compact HostGraph (DLPack-free copy;
    used when the coarsest graph moves to the CPU initial partitioner, per
    BASELINE.json's north star)."""
    n = int(graph.n)
    m = int(graph.m)
    xadj = np.asarray(graph.row_ptr[: n + 1], dtype=np.int64)
    adjncy = np.asarray(graph.dst[:m], dtype=np.int32)
    edge_w = np.asarray(graph.edge_w[:m], dtype=np.int64)
    node_w = np.asarray(graph.node_w[:n], dtype=np.int64)
    from ..caching import record_transfer

    record_transfer(
        "d2h",
        xadj.nbytes + adjncy.nbytes + edge_w.nbytes + node_w.nbytes,
        kind="csr-download",
    )
    return HostGraph(
        xadj=xadj,
        adjncy=adjncy,
        node_weights=None if (node_w == 1).all() else node_w,
        edge_weights=None if m == 0 or (edge_w == 1).all() else edge_w,
    )


# ---------------------------------------------------------------------------
# CSR invariant checker (debug; the output gate's and the chaos suite's
# structural validator)
# ---------------------------------------------------------------------------

ASSERTS_ENV = "KAMINPAR_TPU_ASSERTS"


class CSRInvariantError(ValueError):
    """csr.validate found a structural violation (message says which)."""


def asserts_enabled() -> bool:
    """KAMINPAR_TPU_ASSERTS=1 turns on the debug invariant sweeps
    (maybe_validate at the output gate and at upload boundaries); heavy
    KAMINPAR_TPU_ASSERTION_LEVEL implies it."""
    import os

    if os.environ.get(ASSERTS_ENV, "") == "1":
        return True
    from ..utils.assertions import heavy_assertions_enabled

    return heavy_assertions_enabled()


def maybe_validate(graph, undirected: bool = True, where: str = "") -> None:
    """validate() gated behind KAMINPAR_TPU_ASSERTS=1 (free otherwise)."""
    if not asserts_enabled():
        return
    try:
        validate(graph, undirected=undirected)
    except CSRInvariantError as e:
        raise CSRInvariantError(
            f"{e}{' (at ' + where + ')' if where else ''}"
        ) from None


def _require(cond: bool, what: str) -> None:
    if not cond:
        raise CSRInvariantError(what)


def validate(graph, undirected: bool = True) -> None:
    """Structural CSR invariants for HostGraph, CompressedHostGraph, or
    DeviceGraph; raises CSRInvariantError naming the violated invariant.

    Checks (the graph_validator.cc analog plus this pipeline's dtype and
    padding policy):
      * offsets: start at 0, non-decreasing (not ragged), end at m;
      * adjacency ids in [0, n);
      * dtype policy: int32 ids, int64 host offsets/weights,
        WEIGHT_DTYPE device weights (dtypes.py);
      * undirected graphs: every edge's reverse twin is present;
      * DeviceGraph padding: pad nodes weightless and degree-free, pad
        edges parked on the guaranteed-pad node with weight 0, src
        consistent with row_ptr.
    """
    from .compressed import CompressedHostGraph
    from .host import HostGraph

    if isinstance(graph, CompressedHostGraph):
        return _validate_host_arrays(
            np.asarray(graph.xadj, dtype=np.int64),
            graph.decode().adjncy,
            graph.n,
            undirected,
        )
    if isinstance(graph, HostGraph):
        xadj = np.asarray(graph.xadj)
        _require(
            np.issubdtype(xadj.dtype, np.integer),
            f"dtype policy: xadj must be integer, got {xadj.dtype}",
        )
        _require(
            graph.adjncy.dtype == np.int32,
            f"dtype policy: adjncy must be int32, got {graph.adjncy.dtype}",
        )
        for name in ("node_weights", "edge_weights"):
            w = getattr(graph, name)
            _require(
                w is None or np.issubdtype(np.asarray(w).dtype, np.integer),
                f"dtype policy: {name} must be integer",
            )
        return _validate_host_arrays(
            xadj.astype(np.int64), graph.adjncy, graph.n, undirected,
            edge_w=None if graph.edge_weights is None
            else np.asarray(graph.edge_weights),
        )
    # DeviceGraph
    _require(
        graph.row_ptr.dtype == jnp.int32
        and graph.src.dtype == jnp.int32
        and graph.dst.dtype == jnp.int32,
        "dtype policy: device ids must be int32",
    )
    wdt = jnp.dtype(WEIGHT_DTYPE)
    _require(
        graph.edge_w.dtype == wdt and graph.node_w.dtype == wdt,
        f"dtype policy: device weights must be {wdt}",
    )
    n, m = int(graph.n), int(graph.m)
    n_pad, m_pad = graph.n_pad, graph.m_pad
    _require(n_pad >= n + 1, "padding: n_pad must exceed n (pad node)")
    row_ptr = np.asarray(graph.row_ptr)
    src = np.asarray(graph.src)
    dst = np.asarray(graph.dst)
    _require(
        (row_ptr[n:] == m).all(),
        "padding: row_ptr pad slots must be clamped to m",
    )
    _require(
        (src[m:] == n_pad - 1).all() and (dst[m:] == n_pad - 1).all(),
        "padding: pad edges must be parked on the pad node",
    )
    _require(
        (np.asarray(graph.edge_w)[m:] == 0).all(),
        "padding: pad edges must have weight 0",
    )
    _require(
        (np.asarray(graph.node_w)[n:] == 0).all(),
        "padding: pad nodes must have weight 0",
    )
    deg = np.diff(row_ptr[: n + 1].astype(np.int64))
    _require(
        int(row_ptr[0]) == 0 and (deg >= 0).all() and int(row_ptr[n]) == m,
        "offsets: row_ptr must rise monotonically from 0 to m",
    )
    _require(
        np.array_equal(
            src[:m], np.repeat(np.arange(n, dtype=np.int64), deg)
        ),
        "src/row_ptr mismatch: COO sources disagree with CSR offsets",
    )
    return _validate_host_arrays(
        row_ptr[: n + 1].astype(np.int64), dst[:m], n, undirected,
        edge_w=np.asarray(graph.edge_w)[:m],
    )


def _validate_host_arrays(
    xadj: np.ndarray,
    adjncy: np.ndarray,
    n: int,
    undirected: bool,
    edge_w: Optional[np.ndarray] = None,
) -> None:
    m = int(xadj[-1]) if len(xadj) else 0
    _require(
        len(xadj) == n + 1, f"offsets: xadj has {len(xadj)} entries for n={n}"
    )
    _require(int(xadj[0]) == 0, "offsets: xadj must start at 0")
    _require(
        (np.diff(xadj) >= 0).all(), "offsets: xadj must be non-decreasing"
    )
    _require(
        m == len(adjncy),
        f"offsets: xadj ends at {m} but adjncy has {len(adjncy)} entries",
    )
    if m:
        _require(
            int(adjncy.min()) >= 0 and int(adjncy.max()) < n,
            "adjacency: neighbor id out of [0, n)",
        )
    if undirected and m:
        src = np.repeat(np.arange(n, dtype=np.int64), np.diff(xadj))
        adj64 = adjncy.astype(np.int64)
        fwd = np.lexsort((adj64, src))
        rev = np.lexsort((src, adj64))
        sym = np.array_equal(src[fwd], adj64[rev]) and np.array_equal(
            adj64[fwd], src[rev]
        )
        _require(sym, "symmetry: some edge's reverse twin is missing")
        if sym and edge_w is not None:
            _require(
                np.array_equal(
                    np.asarray(edge_w, dtype=np.int64)[fwd],
                    np.asarray(edge_w, dtype=np.int64)[rev],
                ),
                "symmetry: reverse twin present but weights differ",
            )


def pad_arrays_to(
    n_pad: int, m_pad: int, graph: DeviceGraph
) -> DeviceGraph:
    """Re-pad a device graph into larger buffers (no-op if sizes match).
    Only grows; used to keep hierarchy levels in shared shape buckets."""
    if n_pad == graph.n_pad and m_pad == graph.m_pad:
        return graph
    if n_pad < graph.n_pad or m_pad < graph.m_pad:
        raise ValueError("can only grow padding")
    pad_node = n_pad - 1

    def pad_edges(x, fill):
        return jnp.concatenate(
            [x, jnp.full(m_pad - graph.m_pad, fill, dtype=x.dtype)]
        )

    # re-point old pad slots at the new pad node
    src = jnp.where(jnp.arange(graph.m_pad) < graph.m, graph.src, pad_node)
    dst = jnp.where(jnp.arange(graph.m_pad) < graph.m, graph.dst, pad_node)
    row_ptr = jnp.concatenate(
        [
            graph.row_ptr,
            jnp.full(n_pad - graph.n_pad, graph.m, dtype=graph.row_ptr.dtype),
        ]
    )
    row_ptr = jnp.where(
        jnp.arange(n_pad + 1) <= graph.n, row_ptr, graph.m
    ).astype(jnp.int32)
    return DeviceGraph(
        row_ptr=row_ptr,
        src=pad_edges(src, pad_node),
        dst=pad_edges(dst, pad_node),
        edge_w=pad_edges(graph.edge_w, 0),
        node_w=jnp.concatenate(
            [graph.node_w, jnp.zeros(n_pad - graph.n_pad, dtype=graph.node_w.dtype)]
        ),
        n=graph.n,
        m=graph.m,
    )
