"""BFS region extraction — the BfsExtractor analog.

The reference extracts the `max_hops`-hop BFS region around seed nodes as
a standalone shared-memory graph so a local algorithm (e.g. localized FM)
can run on it, with the *exterior* of the region collapsed into one
pseudo-node per block so the region still feels its attachment to the
rest of the partition (kaminpar-dist/graphutils/bfs_extractor.h:28-46,
bfs_extractor.cc).

TPU split of labor: hop distances come from the device kernel
(ops/bfs.bfs_hops — one segment_min per hop); the region graph itself is
assembled host-side with numpy (region graphs are small by construction —
that is their purpose — so assembly is off the hot path, like the
reference building an shm graph out of the BFS result).

Layout of the extracted graph: region nodes first (in ascending original
id), then k pseudo-nodes (one per block, weight = the block's total
weight outside the region).  Every edge from a region node to an
exterior node is redirected to the exterior node's block pseudo-node,
parallel edges merged by weight sum.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .host import HostGraph, from_edge_list


@dataclass
class BfsExtraction:
    """Result of extract_bfs_subgraph.

    graph        : the region graph (region nodes + k block pseudo-nodes)
    node_mapping : i64[region_size] original node id of each region node
    partition    : i32[graph.n] block of each extracted node (pseudo-node
                   i carries block i)
    num_region   : number of real region nodes (graph.n - k)
    """

    graph: HostGraph
    node_mapping: np.ndarray
    partition: np.ndarray
    num_region: int

    def project_back(self, region_partition: np.ndarray, partition: np.ndarray) -> np.ndarray:
        """Write the region nodes' (possibly changed) blocks back into the
        full partition vector (pseudo-nodes are dropped — they never move).
        Returns the updated full partition."""
        out = partition.copy()
        out[self.node_mapping] = region_partition[: self.num_region]
        return out


def extract_bfs_subgraph(
    host: HostGraph,
    partition: np.ndarray,
    seeds: np.ndarray,
    max_hops: int,
    k: int,
    hops: np.ndarray | None = None,
) -> BfsExtraction:
    """Extract the BFS region around `seeds` with contracted exterior.

    `hops` may be supplied (e.g. np.asarray(ops.bfs.bfs_hops(...))[:n]) to
    reuse a device BFS; otherwise a host BFS is run.  Mirrors
    BfsExtractor::extract (bfs_extractor.cc) with the CONTRACT exterior
    strategy: one pseudo-node per block absorbs all exterior nodes.
    """
    n = host.n
    partition = np.asarray(partition, dtype=np.int32)[:n]
    if hops is None:
        hops = _host_bfs(host, np.asarray(seeds, dtype=np.int64), max_hops)
    else:
        hops = np.asarray(hops, dtype=np.int64)[:n]

    in_region = hops <= max_hops
    region = np.flatnonzero(in_region)
    num_region = len(region)
    # new id: region nodes by ascending original id, then pseudo-nodes
    new_id = np.full(n, -1, dtype=np.int64)
    new_id[region] = np.arange(num_region)

    src = host.edge_sources()
    dst = host.adjncy
    ew = host.edge_weight_array()
    from_region = in_region[src]
    to_region = in_region[dst]

    # interior edges keep both endpoints; boundary edges are redirected to
    # the exterior endpoint's block pseudo-node (id num_region + block)
    keep = from_region
    s0 = new_id[src[keep]]
    d_orig = dst[keep]
    boundary = ~to_region[keep]
    d0 = np.where(
        ~boundary,
        new_id[d_orig],
        num_region + partition[d_orig].astype(np.int64),
    )
    w0 = ew[keep]
    # interior edges already exist in both directions in the CSR; only the
    # redirected boundary edges need their reverse (pseudo -> region) added
    s = np.concatenate([s0, d0[boundary]])
    d = np.concatenate([d0, s0[boundary]])
    w = np.concatenate([w0, w0[boundary]])

    node_weights = np.zeros(num_region + k, dtype=np.int64)
    node_weights[:num_region] = host.node_weight_array()[region]
    # pseudo-node weight = block weight outside the region, so block-weight
    # constraints seen by a local refiner match the global ones
    ext_bw = np.bincount(
        partition[~in_region],
        weights=host.node_weight_array()[~in_region],
        minlength=k,
    ).astype(np.int64)
    node_weights[num_region:] = ext_bw

    edges = np.stack([s, d], axis=1)
    graph = from_edge_list(
        num_region + k,
        edges,
        edge_weights=w,
        node_weights=node_weights,
        symmetrize=False,  # both directions are materialized above
    )
    part_out = np.empty(num_region + k, dtype=np.int32)
    part_out[:num_region] = partition[region]
    part_out[num_region:] = np.arange(k, dtype=np.int32)
    return BfsExtraction(
        graph=graph,
        node_mapping=region,
        partition=part_out,
        num_region=num_region,
    )


def _host_bfs(host: HostGraph, seeds: np.ndarray, max_hops: int) -> np.ndarray:
    """Simple host-side BFS fallback (same semantics as ops/bfs.bfs_hops)."""
    n = host.n
    INF = np.iinfo(np.int64).max
    dist = np.full(n, INF, dtype=np.int64)
    seeds = seeds[(seeds >= 0) & (seeds < n)]
    dist[seeds] = 0
    frontier = seeds
    for h in range(max_hops):
        nxt = []
        for u in frontier:
            for v in host.neighbors(u):
                if dist[v] == INF:
                    dist[v] = h + 1
                    nxt.append(v)
        if not nxt:
            break
        frontier = np.asarray(nxt, dtype=np.int64)
    return dist
