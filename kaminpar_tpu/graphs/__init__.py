from .host import (  # noqa: F401
    HostGraph,
    from_csr,
    from_edge_list,
    validate,
    degree_bucket_permutation,
    apply_permutation,
    remove_isolated_nodes,
    count_isolated_nodes,
    extract_block_subgraphs,
    NodePermutation,
)
from .csr import (  # noqa: F401
    DeviceGraph,
    device_graph_from_host,
    host_graph_from_device,
)
from . import factories  # noqa: F401
