"""Out-of-core streaming partitioner (``--scheme external``).

ROADMAP item 4 made real: the fine graph lives in host RAM (compressed
chunks, plain CSR, or a skagen generator spec regenerated chunk by
chunk) or on disk, and LP rating + contraction run ON DEVICE over
fixed-shape padded edge-block chunks — only coarse levels are ever
device-resident.  The semi-external scheme of arXiv 1404.4887 mapped
onto the padded-bucket device pipeline; Tera-Scale MGP (arXiv
2410.19119) is the evidence the multilevel scheme survives this
externalization without giving up quality.

Three modules:

  * :mod:`~kaminpar_tpu.external.chunkstore` — the node-range chunk
    plan and sources (HostGraph / CompressedHostGraph / generator
    spec), one shared padded edge-block bucket for the whole stream,
    and the disk spill tier;
  * :mod:`~kaminpar_tpu.external.stream_coarsen` — the device-streamed
    bulk-synchronous LP rounds (label + cluster-weight vectors are the
    only fine-graph-sized device state) and the chunked contraction
    that accumulates the coarse CSR host-side;
  * :mod:`~kaminpar_tpu.external.driver` — the ``--scheme external``
    driver: streamed levels with checkpoint barriers, the in-core
    handoff to the deep pipeline, and the schema-v9 ``external`` report
    section.
"""

from .chunkstore import ChunkStore, StreamedSpecGraph  # noqa: F401
from .driver import ExternalPartitioner, external_partition  # noqa: F401
