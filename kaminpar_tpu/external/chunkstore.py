"""Node-range chunk plan + sources for the out-of-core stream.

The streaming contract every consumer (stream_coarsen, the external
driver, the gate's streamed recompute) relies on:

  * chunks are **contiguous node ranges**, so every node's full
    neighborhood lives in exactly one chunk — per-node ratings computed
    from one chunk are *exact*, never partial;
  * every chunk of a level is padded into **one shared edge-block
    bucket** (the largest chunk, padded through ``caching.pad_size``
    under the active pad policy), so the whole stream reuses ONE
    compiled executable per phase instead of minting a bucket per
    chunk;
  * sources are **re-iterable**: compressed graphs re-decode
    (``decode_range``), plain CSRs re-slice, skagen generator specs
    re-generate (chunk determinism means the synthetic fine graph is
    never materialized at all), and the optional disk **spill tier**
    writes each chunk once and re-reads it per pass — fine graphs
    bigger than host RAM stream from disk.

Host pulls (decode, np.asarray of device results) are deliberately
factored into the helpers here so driver code can call them from inside
its timer spans without tripping tpulint R1 (the same hook shape as
telemetry/quality.py — pinned by tests/lint_fixtures/r1_stream_*.py).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

#: Granularity of the shared edge-block bucket (slots).  Small enough
#: that the tight pad policy keeps chunk buffers lean under the memory
#: ladder, large enough that the bucket count stays O(1) per stream.
EDGE_BUCKET_GRANULARITY = 4096


def chunk_ranges(n: int, num_chunks: int) -> List[Tuple[int, int]]:
    """Contiguous node ranges [v0, v1) — the same divmod split as
    io/skagen.StreamedGraph.chunk_range, shared here so generator-backed
    stores and CSR-backed stores chunk identically."""
    num_chunks = max(1, min(int(num_chunks), max(int(n), 1)))
    base, rem = divmod(int(n), num_chunks)
    out = []
    v0 = 0
    for c in range(num_chunks):
        v1 = v0 + base + (1 if c < rem else 0)
        out.append((v0, v1))
        v0 = v1
    return out


@dataclass
class ChunkBlock:
    """One padded edge-block chunk, host-side, ready for device upload.

    ``src_local`` is the row id RELATIVE to ``v0`` (in [0, span)); pad
    slots carry ``src_local == span`` (the phantom row the kernels route
    to an overflow segment), ``dst == 0`` and ``w == 0`` so they
    contribute nothing to ratings or contractions."""

    v0: int
    v1: int
    src_local: np.ndarray  # i32[e_pad]
    dst: np.ndarray  # i32[e_pad], global neighbor ids
    w: np.ndarray  # WEIGHT[e_pad]
    m_real: int


class _HostCSRSource:
    """Rows from a plain HostGraph (already RAM-resident; the stream
    still buys executable reuse and a bounded device footprint)."""

    def __init__(self, graph):
        self.graph = graph
        self.xadj = np.asarray(graph.xadj, dtype=np.int64)

    def rows(self, v0: int, v1: int):
        lo, hi = int(self.xadj[v0]), int(self.xadj[v1])
        adj = np.asarray(self.graph.adjncy[lo:hi])
        ew = self.graph.edge_weights
        return adj, (None if ew is None else np.asarray(ew[lo:hi]))


class _CompressedSource:
    """Rows decoded on demand from a CompressedHostGraph
    (graphs/compressed.decode_range: peak host memory is one chunk)."""

    def __init__(self, cgraph):
        self.graph = cgraph
        self.xadj = np.asarray(cgraph.xadj, dtype=np.int64)

    def rows(self, v0: int, v1: int):
        _, adj, ew = self.graph.decode_range(v0, v1)
        return np.asarray(adj), (None if ew is None else np.asarray(ew))


class _GeneratorSource:
    """Rows regenerated from a skagen StreamedGraph whose chunk grid the
    plan ADOPTS 1:1 — ``rows`` only ever asks for a grid range, so each
    call regenerates exactly one deterministic generator chunk and the
    flat fine graph never exists anywhere."""

    def __init__(self, sg, xadj: np.ndarray):
        self.sg = sg
        self.xadj = np.asarray(xadj, dtype=np.int64)
        self._ranges = {sg.chunk_range(c): c for c in range(sg.num_chunks)}

    def rows(self, v0: int, v1: int):
        c = self._ranges.get((v0, v1))
        if c is None:
            raise ValueError(
                f"generator source only serves its own grid ranges, "
                f"not [{v0}, {v1})"
            )
        ch = self.sg.chunk(c)
        w = np.asarray(ch.adjwgt, dtype=np.int64)
        return np.asarray(ch.adjncy), (None if (w == 1).all() else w)


class ChunkStore:
    """The chunk plan + padded-block reader over one fine graph.

    Built by :func:`build_store`.  ``num_chunks`` is sized so the
    average chunk carries ~``target_edges`` edges; ``e_pad`` (the shared
    bucket) pads the LARGEST chunk, so skewed node ranges cost padding,
    never a second executable.  Counters (``decoded_bytes``,
    ``uploaded_bytes``, ``spilled_bytes``) feed the ``stream`` telemetry
    events and the report's ``external`` section."""

    def __init__(self, source, n: int, m: int,
                 ranges: List[Tuple[int, int]], spill_dir: str = ""):
        from .. import caching

        self.source = source
        self.n = int(n)
        self.m = int(m)
        self.ranges = ranges
        self.num_chunks = len(ranges)
        self.span = max((v1 - v0) for v0, v1 in ranges) if ranges else 1
        xadj = source.xadj
        max_edges = max(
            (int(xadj[v1] - xadj[v0]) for v0, v1 in ranges), default=1
        )
        self.e_pad = caching.pad_size(
            max(max_edges, 1), EDGE_BUCKET_GRANULARITY
        )
        self.spill_dir = spill_dir
        self.decoded_bytes = 0
        self.uploaded_bytes = 0
        self.spilled_bytes = 0
        # per-chunk content digest of the spilled file (written by this
        # process), verified on every re-read — a corrupted spill is a
        # classified IntegrityViolation recovered by re-decoding
        self._spill_sha: Dict[int, str] = {}
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
            self._validate_spill_dir()

    def _spill_key(self) -> str:
        """Identity of the (graph, chunk plan) the spill dir's files are
        valid for: sizes, plan geometry, and a degree-prefix sample —
        a chunk file from a different graph, chunk target, or
        budget-shrunk plan must never be re-read as this one's rows."""
        import hashlib

        xadj = np.asarray(self.source.xadj, dtype=np.int64)
        h = hashlib.sha256()
        # "v2": spill files moved from bare np.savez to the checksummed
        # io/snapshot format — a v1 dir must be dropped, not re-read
        h.update(
            f"v2;n={self.n};m={self.m};chunks={self.num_chunks};"
            f"span={self.span};e_pad={self.e_pad};".encode()
        )
        h.update(xadj[:2048].tobytes())
        h.update(xadj[-2048:].tobytes())
        return h.hexdigest()[:24]

    def _validate_spill_dir(self) -> None:
        """The spill dir is a CACHE keyed by :meth:`_spill_key`: a key
        mismatch (different graph / chunk plan reusing the dir) drops
        every stale chunk file instead of silently serving another
        run's rows."""
        meta_path = os.path.join(self.spill_dir, "spill.json")
        key = self._spill_key()
        try:
            import json

            with open(meta_path) as f:
                if json.load(f).get("key") == key:
                    return
        except (OSError, ValueError):
            pass
        for fn in os.listdir(self.spill_dir):
            if fn.startswith("chunk-") and fn.endswith(".npz"):
                try:
                    os.unlink(os.path.join(self.spill_dir, fn))
                except OSError:
                    pass
        import json

        tmp = meta_path + f".{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"key": key, "n": self.n, "m": self.m,
                       "chunks": self.num_chunks}, f)
        os.replace(tmp, meta_path)

    # -- host side -------------------------------------------------------

    def chunk_edges(self, c: int) -> int:
        v0, v1 = self.ranges[c]
        xadj = self.source.xadj
        return int(xadj[v1] - xadj[v0])

    def _rows(self, c: int):
        """(adjncy, edge_w|None) of chunk c, through the spill tier when
        one is configured: first touch writes the decoded chunk to disk,
        later passes re-read it instead of re-decoding/regenerating."""
        v0, v1 = self.ranges[c]
        if self.spill_dir:
            from ..io.snapshot import (
                SnapshotError, read_snapshot, write_snapshot,
            )
            from ..resilience import integrity

            path = os.path.join(self.spill_dir, f"chunk-{c}.npz")
            if os.path.exists(path):
                # `spill-corrupt` chaos mutates the at-rest bytes; the
                # per-chunk digest recorded at spill time is what the
                # re-read verifies (sha checked BEFORE np.load, so a
                # flipped bit is a digest mismatch, not a zip error)
                integrity.chaos_flip_file("spill-corrupt", path)
                expect = (
                    self._spill_sha.get(c) if integrity.enabled() else None
                )
                try:
                    z = read_snapshot(path, expect)
                except (SnapshotError, OSError, ValueError) as exc:
                    # corrupted spill file: a classified integrity
                    # violation with a LOCAL recovery — drop the file
                    # and re-decode from the source (the spill tier is
                    # a cache; with_fallback has no business here)
                    integrity.note_digest_mismatch(
                        f"spill:chunk-{c}", str(exc), site="spill-corrupt"
                    )
                    self._spill_sha.pop(c, None)
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                else:
                    adj = z["adjncy"]
                    ew = z.get("edge_w")
                    self.decoded_bytes += int(adj.nbytes) + (
                        0 if ew is None else int(ew.nbytes)
                    )
                    return adj, ew
            adj, ew = self.source.rows(v0, v1)
            arrays = {"adjncy": adj}
            if ew is not None:
                arrays["edge_w"] = ew
            # checksummed snapshot format (io/snapshot.py): atomic
            # write, content sha stored for the re-read verification
            _, self._spill_sha[c] = write_snapshot(path, arrays)
            self.spilled_bytes += int(adj.nbytes) + (
                0 if ew is None else int(ew.nbytes)
            )
        else:
            adj, ew = self.source.rows(v0, v1)
        self.decoded_bytes += int(adj.nbytes) + (
            0 if ew is None else int(ew.nbytes)
        )
        return adj, ew

    def chunk_host(self, c: int) -> ChunkBlock:
        """Chunk c decoded + padded into the shared bucket (numpy)."""
        from ..dtypes import WEIGHT_DTYPE

        v0, v1 = self.ranges[c]
        adj, ew = self._rows(c)
        xadj = self.source.xadj
        deg = np.diff(xadj[v0 : v1 + 1])
        m_real = int(len(adj))
        src_local = np.full(self.e_pad, self.span, dtype=np.int32)
        src_local[:m_real] = np.repeat(
            np.arange(v1 - v0, dtype=np.int32), deg
        )
        dst = np.zeros(self.e_pad, dtype=np.int32)
        dst[:m_real] = np.asarray(adj, dtype=np.int32)
        w = np.zeros(self.e_pad, dtype=np.dtype(WEIGHT_DTYPE))
        if ew is None:
            w[:m_real] = 1
        else:
            w[:m_real] = np.asarray(ew).astype(np.dtype(WEIGHT_DTYPE))
        return ChunkBlock(v0, v1, src_local, dst, w, m_real)

    # -- device side -----------------------------------------------------

    def upload(self, c: int):
        """Decode + upload chunk c; returns device arrays
        ``(src_local, dst, w, v0_dev, m_real_dev)``.  Dispatch is async:
        the caller chains device work onto these without a host sync, so
        the NEXT chunk's decode overlaps this chunk's compute."""
        import jax
        import jax.numpy as jnp

        block = self.chunk_host(c)
        nbytes = (
            int(block.src_local.nbytes) + int(block.dst.nbytes)
            + int(block.w.nbytes)
        )
        self.uploaded_bytes += nbytes
        from ..caching import record_transfer

        record_transfer("h2d", nbytes, kind="chunk-upload")
        return (
            jax.device_put(block.src_local),
            jax.device_put(block.dst),
            jax.device_put(block.w),
            jnp.int32(block.v0),
            jnp.int32(block.m_real),
        )

    def chunk_buffer_bytes(self) -> int:
        """Device bytes one uploaded chunk occupies (the stream's whole
        edge footprint: fine edges are never resident beyond this)."""
        from ..dtypes import WEIGHT_DTYPE

        return int(self.e_pad * (4 + 4 + np.dtype(WEIGHT_DTYPE).itemsize))


def build_store(graph, target_edges: int, spill_dir: str = "") -> ChunkStore:
    """The chunk plan for one fine graph: ``ceil(m / target_edges)``
    contiguous node ranges over a Host CSR, a compressed container, or a
    generator-spec wrapper (which brings its own grid)."""
    from ..graphs.compressed import CompressedHostGraph
    from ..graphs.host import HostGraph

    n, m = int(graph.n), int(graph.m)
    num_chunks = max(1, -(-m // max(int(target_edges), 1)))
    if isinstance(graph, StreamedSpecGraph):
        sg = graph.grid(num_chunks)
        src = _GeneratorSource(sg, graph.xadj)
        ranges = [sg.chunk_range(c) for c in range(sg.num_chunks)]
        return ChunkStore(src, n, m, ranges, spill_dir=spill_dir)
    if isinstance(graph, CompressedHostGraph):
        src = _CompressedSource(graph)
    elif isinstance(graph, HostGraph):
        src = _HostCSRSource(graph)
    else:
        raise TypeError(
            f"no chunk source for {type(graph).__name__} "
            "(HostGraph, CompressedHostGraph, or StreamedSpecGraph)"
        )
    return ChunkStore(src, n, m, chunk_ranges(n, num_chunks),
                      spill_dir=spill_dir)


# ---------------------------------------------------------------------------
# generator-spec fine graphs (never materialized)
# ---------------------------------------------------------------------------


class StreamedSpecGraph:
    """A skagen generator spec wearing the HostGraph surface the facade
    needs (n / m / xadj / weights / degrees) WITHOUT ever holding the
    adjacency: one deterministic generation pass at construction records
    the O(n) degree prefix, and every later consumer (the chunk store,
    the gate's streamed recompute) regenerates chunks on demand —
    skagen's chunk determinism guarantees every pass sees the same
    graph."""

    def __init__(self, spec: str, target_edges: int = 1 << 22):
        from ..graphs.factories import parse_gen_spec
        from ..io import skagen

        self.spec = spec
        # size the stats-pass grid from the SPEC's own edge estimate so
        # its peak memory honors the target budget too (a fixed small
        # grid would materialize O(m / grid) edges per probe chunk —
        # unbounded on the tera-scale inputs this wrapper exists for)
        try:
            _, kw = parse_gen_spec(spec)
            m_est = int(kw.get("m") or (
                float(kw.get("n", 1)) * float(kw.get("avg_degree", 8.0))
            ))
        except Exception:
            m_est = 0
        probe_chunks = max(8, -(-max(m_est, 1) // max(int(target_edges), 1)))
        probe = skagen.streamed(spec, num_chunks=probe_chunks)
        self.kind = probe.kind
        self._n = probe.n
        xadj = np.zeros(probe.n + 1, dtype=np.int64)
        tew = 0
        unit = True
        for ch in probe.chunks():
            deg = np.asarray(ch.xadj[1:]) - np.asarray(ch.xadj[:-1])
            xadj[ch.v_begin + 1 : ch.v_end + 1] = deg
            w = np.asarray(ch.adjwgt, dtype=np.int64)
            tew += int(w.sum())
            if unit and len(w) and not (w == 1).all():
                unit = False
        np.cumsum(xadj, out=xadj)
        self.xadj = xadj
        self._m = int(xadj[-1])
        self._total_edge_weight = tew
        self._unit_edge_weights = unit
        self._probe = probe
        self.node_weights = None
        self.edge_weights = None  # per-chunk only; see iter_rows
        self.target_edges = int(target_edges)

    # -- HostGraph surface ----------------------------------------------

    @property
    def n(self) -> int:
        return self._n

    @property
    def m(self) -> int:
        return self._m

    def degrees(self) -> np.ndarray:
        return (self.xadj[1:] - self.xadj[:-1]).astype(np.int64)

    def node_weight_array(self) -> np.ndarray:
        return np.ones(self._n, dtype=np.int64)

    @property
    def total_node_weight(self) -> int:
        return self._n

    @property
    def total_edge_weight(self) -> int:
        return self._total_edge_weight

    # -- streaming surface ----------------------------------------------

    def grid(self, num_chunks: int):
        """A StreamedGraph over the SAME spec/seed with the requested
        chunk grid (chunk determinism: the assembled graph is identical
        for any grid)."""
        from ..io import skagen

        return skagen.streamed(self.spec, num_chunks=num_chunks)

    def iter_rows(self, target_edges: Optional[int] = None) -> Iterator[
        Tuple[int, int, np.ndarray, Optional[np.ndarray]]
    ]:
        """Yield (v0, v1, adjncy, edge_w|None) node-range blocks — the
        streamed-metrics surface (gate recompute, result metrics)."""
        te = int(target_edges or self.target_edges)
        sg = self.grid(max(1, -(-self._m // max(te, 1))))
        for ch in sg.chunks():
            w = np.asarray(ch.adjwgt, dtype=np.int64)
            ew = None if (len(w) == 0 or (w == 1).all()) else w
            yield ch.v_begin, ch.v_end, np.asarray(ch.adjncy), ew

    def to_host_graph(self):
        """Materialize the full CSR (the rare paths only: gate repair,
        non-external schemes) — the one operation that costs the flat
        edge list this wrapper otherwise never holds."""
        from ..io import skagen

        return skagen.hostgraph_from_stream(self._probe)


def streamed_partition_metrics(graph: StreamedSpecGraph, partition,
                               k: int) -> dict:
    """host_partition_metrics over a generator-spec graph without
    materializing it: the cut accumulates over regenerated chunks —
    the StreamedSpecGraph twin of
    graphs.compressed.compressed_partition_metrics (same definitions,
    same RESULT-line semantics)."""
    partition = np.asarray(partition)
    cut = 0
    for v0, v1, adj, ew in graph.iter_rows():
        deg = (graph.xadj[v0 + 1 : v1 + 1] - graph.xadj[v0:v1])
        src = np.repeat(np.arange(v0, v1, dtype=np.int64), deg)
        mask = partition[src] != partition[adj]
        cut += int(mask.sum() if ew is None else np.asarray(ew)[mask].sum())
    nw = graph.node_weight_array()
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, partition, nw)
    perfect = max(1, -(-int(nw.sum()) // max(k, 1)))
    return {
        "cut": cut // 2,
        "block_weights": bw,
        "imbalance": bw.max() / perfect - 1.0 if k else 0.0,
    }


# ---------------------------------------------------------------------------
# host-pull helpers for the streaming kernels (keep driver spans R1-clean)
# ---------------------------------------------------------------------------


def pull_moved(moved) -> int:
    """One scalar readback at a round boundary (the stream's only
    per-round host sync — this is where the async chunk pipeline
    drains)."""
    from ..caching import record_transfer

    record_transfer("d2h", getattr(moved, "nbytes", 8), kind="stat-pull")
    return int(moved)


def pull_labels(labels, n: int) -> np.ndarray:
    """The converged label vector, host-side (one n-sized pull per
    streamed level, at the LP -> contraction boundary)."""
    out = np.asarray(labels[:n], dtype=np.int64)
    from ..caching import record_transfer

    record_transfer("d2h", out.nbytes, kind="chunk-pull")
    return out


def pull_coarse_groups(cu_g, cv_g, w_g) -> Tuple[np.ndarray, np.ndarray,
                                                 np.ndarray]:
    """One chunk's deduplicated coarse edges, host-side, compacted to
    the valid groups."""
    cu = np.asarray(cu_g)
    cv = np.asarray(cv_g)
    w = np.asarray(w_g)
    from ..caching import record_transfer

    record_transfer(
        "d2h", cu.nbytes + cv.nbytes + w.nbytes, kind="chunk-pull"
    )
    keep = cu >= 0
    return (
        cu[keep].astype(np.int64),
        cv[keep].astype(np.int64),
        w[keep].astype(np.int64),
    )
