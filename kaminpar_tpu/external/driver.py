"""The ``--scheme external`` driver: streamed levels, checkpoint
barriers, governor integration, and the in-core handoff.

Flow (the out-of-core half of the deep pipeline):

  1. size the chunk plan against the declared memory budget (the chunk
     target shrinks until the stream state — one double-buffered edge
     block + the O(n) label/weight vectors — fits; if even the floor
     chunk cannot, a structured DeviceOOM sends the facade's ladder on);
  2. stream-coarsen level by level (stream_coarsen.py) until the coarse
     level's ``memory.estimate_run_bytes`` fits the budget (with no
     budget: ``ctx.external.min_stream_levels`` levels, so the fine
     level is never device-resident either way), crossing a
     ``stream-coarsen`` checkpoint barrier after every contraction —
     a kill mid-stream resumes at the completed level, cut-identical;
  3. hand the coarse graph to the UNCHANGED deep pipeline (its own
     barriers/resume/refinement apply; the streamed level snapshots are
     *pinned* in the checkpoint manifest so a kill during the in-core
     phase still restores the projection maps);
  4. project the partition back through the host-side cluster maps.

Every run annotates the schema-v9 ``external`` report section: chunk
counts, decoded vs uploaded bytes, the upload/compute overlap fraction,
and ``fine_device_resident_bytes`` (0 whenever >= 1 level streamed —
the bytes a fine-level upload would have cost are reported next to it).
"""

from __future__ import annotations

from typing import Any, List, Tuple

import numpy as np

from ..context import Context
from ..utils import timer
from ..utils.logger import log_progress

#: Floor for the budget-driven chunk shrink: below this many edges per
#: chunk the per-chunk launch overhead dominates any memory win.
MIN_CHUNK_EDGES = 1 << 15


class ExternalPartitioner:
    """Out-of-core streaming partitioner (scheme ``external``)."""

    def __init__(self, ctx: Context):
        self.ctx = ctx

    # -- entry -----------------------------------------------------------

    def partition(self, graph) -> np.ndarray:
        from .. import telemetry
        from ..resilience import checkpoint as ckpt
        from ..resilience import memory as memory_mod
        from ..resilience.errors import DeviceOOM

        ctx = self.ctx
        ext = ctx.external
        k = int(ctx.partition.k)
        n, m = int(graph.n), int(graph.m)
        budget = memory_mod.budget_bytes(ctx)

        # chunk sizing: shrink the target until the stream state fits
        # (an explicitly configured smaller target is honored as-is;
        # the floor only bounds the budget-driven shrink)
        chunk_edges = max(1, int(ext.chunk_edges))
        if budget:
            while (
                chunk_edges > MIN_CHUNK_EDGES
                and memory_mod.estimate_stream_bytes(n, chunk_edges, k)
                > budget
            ):
                chunk_edges //= 2
            if memory_mod.estimate_stream_bytes(n, chunk_edges, k) > budget:
                raise DeviceOOM(
                    f"external preflight: floor stream state "
                    f"{memory_mod.estimate_stream_bytes(n, chunk_edges, k)} "
                    f"bytes exceeds the budget {budget} (n={n}, k={k})",
                    site="device-oom",
                )
        target = (
            int(budget * memory_mod.STREAM_TARGET_FRACTION)
            if budget else None
        )

        cmaps, current, start_level = self._take_resume(graph)
        if current is None:
            current = graph

        import time as _time

        t_stream0 = _time.perf_counter()
        levels_meta: List[dict] = []
        level = start_level
        stop_requested = False
        with timer.scoped_timer("external-stream"):
            while True:
                n_c, m_c = _sizes(current)
                fits = (
                    target is None
                    or memory_mod.estimate_run_bytes(n_c, m_c, k) <= target
                )
                # even with a roomy budget the external scheme streams
                # its minimum level count — the fine level is never
                # device-resident unless the input is already tiny
                satisfied = fits and (
                    level >= max(0, int(ext.min_stream_levels))
                )
                if (
                    satisfied
                    or stop_requested
                    or level >= int(ext.max_stream_levels)
                    or n_c <= max(2 * ctx.coarsening.contraction_limit, 2)
                ):
                    break
                # the coarsener's per-level cap formula, derived from
                # the LEVEL's node count — deterministic from the level
                # inputs, so a resumed run re-derives identical caps
                cap = max(
                    1,
                    int(ctx.coarsening.max_cluster_weight(
                        n_c, int(ctx.partition.total_node_weight),
                        ctx.partition,
                    )),
                )
                coarse, cmap, meta = self._stream_level(
                    current, level, cap, chunk_edges
                )
                if coarse is None:
                    break  # clustering stalled even under the relaxed cap
                cmaps.append(cmap)
                current = coarse
                levels_meta.append(meta)
                stop_requested = not ckpt.barrier(
                    "stream-coarsen", level=level, scheme="external",
                    payload=_level_payload(level, coarse, cmap),
                    keep=[f"stream-level-{j}" for j in range(level)],
                    meta={"stream_levels": level + 1},
                )
                _pin_level(level)
                level += 1

        handoff = self._handoff_graph(current)
        h_n, h_m = _sizes(handoff)
        telemetry.annotate(external=_section(
            levels_meta, cmaps, graph, handoff_n=h_n, handoff_m=h_m,
            streamed=len(cmaps), resumed=start_level, k=k,
        ))
        # request tracing: a serving request routed to the external
        # scheme gets the stream phase as its own span (streamed level
        # count + handoff size next to the in-core compute that follows)
        from ..telemetry import tracing

        tracing.span(
            tracing.current(), "external-stream", start=t_stream0,
            duration_s=_time.perf_counter() - t_stream0,
            origin="external", streamed=len(cmaps), handoff_n=h_n,
        )
        log_progress(
            f"external: streamed {len(cmaps)} level(s) down to "
            f"n={h_n} m={h_m}; handing off to the in-core deep pipeline"
        )

        # in-core handoff: the UNCHANGED device pipeline, with its own
        # preflight, barriers, refinement, and (inside the facade) gate
        part = self._incore_partition(handoff)
        with timer.scoped_timer("external-projection"):
            part = _project(part, cmaps)
        return np.asarray(part, dtype=np.int32)[: graph.n]

    def _incore_partition(self, handoff) -> np.ndarray:
        """The in-core phase over the coarse graph.  The external
        scheme's own handoff is the deep pipeline; when the MEMORY
        LADDER rerouted rung 3 here from another scheme, that scheme's
        driver runs instead (the semi_external_partition dispatch
        contract it replaced)."""
        from ..context import PartitioningMode

        mode = self.ctx.partitioning.mode
        if mode == PartitioningMode.KWAY:
            from ..partitioning.kway import KWayMultilevelPartitioner

            return KWayMultilevelPartitioner(self.ctx).partition(handoff)
        if mode == PartitioningMode.RB:
            from ..partitioning.rb_scheme import RBMultilevelPartitioner

            return RBMultilevelPartitioner(self.ctx).partition(handoff)
        if mode == PartitioningMode.VCYCLE:
            from ..partitioning.vcycle import (
                VcycleDeepMultilevelPartitioner,
            )

            return VcycleDeepMultilevelPartitioner(self.ctx).partition(
                handoff
            )
        from ..partitioning.deep import DeepMultilevelPartitioner

        return DeepMultilevelPartitioner(self.ctx).partition(handoff)

    # -- one streamed level ---------------------------------------------

    def _stream_level(self, graph, level: int, cap: int,
                      chunk_edges: int) -> Tuple[Any, Any, dict]:
        """Stream-coarsen one level: LP rounds + chunked contraction.
        Returns (coarse HostGraph | None on stall, cmap, meta)."""
        from .. import telemetry
        from . import chunkstore, stream_coarsen

        ext = self.ctx.external
        spill = ext.spill_dir if level == 0 else ""
        store = chunkstore.build_store(graph, chunk_edges, spill_dir=spill)
        node_weights = getattr(graph, "node_weights", None)
        seed = (int(self.ctx.seed) * 31 + level * 9973) & 0x7FFFFFFF

        labels_host, lp_stats, cap_used = self._cluster_level(
            store, node_weights, cap, seed
        )
        c_n = int(np.unique(labels_host).size)
        stalled = c_n >= stream_coarsen.STALL_FRACTION * store.n
        if stalled:
            return None, None, {}
        with timer.scoped_timer("stream-contract"):
            coarse, cmap, ct_stats = stream_coarsen.stream_contract(
                store, labels_host, node_weights
            )
        decode_s = lp_stats["decode_s"] + ct_stats["decode_s"]
        drain_s = lp_stats["drain_s"] + ct_stats["drain_s"]
        meta = {
            "level": level,
            "chunks": store.num_chunks,
            "fine_n": store.n,
            "fine_m": store.m,
            "coarse_n": int(coarse.n),
            "coarse_m": int(coarse.m),
            "rounds": lp_stats["rounds"],
            "moved": lp_stats["moved"],
            "cap": cap_used,
            "decoded_bytes": store.decoded_bytes,
            "uploaded_bytes": store.uploaded_bytes,
            "spilled_bytes": store.spilled_bytes,
            "chunk_buffer_bytes": store.chunk_buffer_bytes(),
            "decode_s": round(decode_s, 4),
            "drain_s": round(drain_s, 4),
            "overlap_frac": _overlap(decode_s, drain_s),
        }
        telemetry.event("stream", **meta)
        log_progress(
            f"external level {level}: n={coarse.n} m={coarse.m} "
            f"({store.num_chunks} chunk(s), overlap "
            f"{meta['overlap_frac']:.2f})"
        )
        return coarse, cmap, meta

    def _cluster_level(self, store, node_weights, cap: int, seed: int):
        """Streaming LP with the stall-relax retry (the coarsener's
        forced-shrink idiom): a clustering that barely shrinks re-runs
        once under a doubled cluster-weight cap.  Cap relaxation is
        LOCAL to the level, so a resumed run re-derives the same caps."""
        from . import chunkstore, stream_coarsen

        rounds = int(self.ctx.external.lp_rounds)
        cap_used = cap
        for attempt in range(2):
            labels, cluster_w, node_w = stream_coarsen.make_vectors(
                store, node_weights
            )
            with timer.scoped_timer("stream-lp"):
                labels, cluster_w, lp_stats = stream_coarsen.stream_lp(
                    store, labels, cluster_w, node_w, cap_used, seed, rounds
                )
            labels_host = chunkstore.pull_labels(labels, store.n)
            c_n = int(np.unique(labels_host).size)
            if c_n < stream_coarsen.STALL_FRACTION * store.n or attempt:
                break
            cap_used = cap_used * 2
        return labels_host, lp_stats, cap_used

    # -- handoff / resume ------------------------------------------------

    def _handoff_graph(self, current):
        """The graph the in-core deep pipeline receives.  A generator
        wrapper that never streamed (tiny input) materializes here —
        the one case the fine level becomes device-resident, reported
        as such in the `external` section."""
        from .chunkstore import StreamedSpecGraph

        if isinstance(current, StreamedSpecGraph):
            return current.to_host_graph()
        return current

    def _take_resume(self, graph):
        """Re-enter mid-stream: restore the completed streamed levels'
        cluster maps + the newest coarse CSR from the checkpoint.

        Two kill sites resolve differently: a kill at a
        ``stream-coarsen`` barrier left scheme="external" — the resume
        state is CONSUMED here and streaming continues at the next
        level; a kill during the in-core phase left scheme="deep" — the
        pinned stream-level snapshots are only PEEKED (pending_resume)
        so the deep driver can still consume its own state and re-enter
        its hierarchy."""
        from .. import telemetry
        from ..graphs.host import HostGraph
        from ..resilience import checkpoint as ckpt

        arrays = None
        res = ckpt.take_resume("external")
        if res is not None:
            arrays = res.get("arrays")
        else:
            mgr = ckpt.active()
            if mgr is not None and not ckpt.suspended():
                pend = mgr.pending_resume()
                if pend is not None:
                    arrays = pend.get("arrays")
        if not arrays:
            return [], None, 0
        names = sorted(
            (nm for nm in arrays if nm.startswith("stream-level-")),
            key=lambda s: int(s.rsplit("-", 1)[1]),
        )
        if not names:
            return [], None, 0
        cmaps = [
            np.asarray(arrays[nm]["cmap"], dtype=np.int32) for nm in names
        ]
        last = arrays[names[-1]]
        edge_w = last["edge_w"]
        coarse = HostGraph(
            xadj=np.asarray(last["xadj"], dtype=np.int64),
            adjncy=np.asarray(last["adjncy"], dtype=np.int32),
            node_weights=np.asarray(last["node_w"], dtype=np.int64),
            edge_weights=(
                np.asarray(edge_w, dtype=np.int64) if edge_w.size else None
            ),
        )
        mgr = ckpt.active()
        if mgr is not None:
            mgr.pin(names)
        telemetry.event(
            "resume", scheme="external", stage="stream-coarsen",
            level=len(names) - 1, levels_restored=len(names),
        )
        log_progress(
            f"resumed external stream at level {len(names)} "
            f"({len(names)} streamed level(s) restored)"
        )
        return cmaps, coarse, len(names)


# ---------------------------------------------------------------------------
# module-level helpers (host pulls live OUTSIDE the driver's timer spans —
# the tpulint R1 hook shape, pinned by tests/lint_fixtures/r1_stream_*.py)
# ---------------------------------------------------------------------------


def _sizes(graph) -> Tuple[int, int]:
    return int(graph.n), int(graph.m)


def _project(part: np.ndarray, cmaps: List[np.ndarray]) -> np.ndarray:
    # `part` is already host (np.ndarray contract) — a dtype cast, not a
    # device pull
    part = part.astype(np.int32, copy=False)
    for cmap in reversed(cmaps):
        part = part[cmap]
    return part


def _overlap(decode_s: float, drain_s: float) -> float:
    """Upload/compute overlap fraction: the share of host-side stream
    work (chunk decode + upload dispatch) that ran while the device's
    async queue was busy, i.e. NOT spent blocked draining the device.
    1.0 = the host never waited; 0.0 = fully serialized."""
    total = decode_s + drain_s
    return round(decode_s / total, 4) if total > 0 else 0.0


def _level_payload(level: int, coarse, cmap):
    """Deferred checkpoint payload for one streamed level (built only
    when a checkpoint manager is armed)."""
    def build():
        return {f"stream-level-{level}": {
            "xadj": np.asarray(coarse.xadj, dtype=np.int64),
            "adjncy": np.asarray(coarse.adjncy, dtype=np.int32),
            "node_w": np.asarray(coarse.node_weight_array(), dtype=np.int64),
            "edge_w": np.asarray(coarse.edge_weight_array(), dtype=np.int64),
            "cmap": np.asarray(cmap, dtype=np.int32),
            "dims": np.asarray(
                [len(cmap), int(coarse.n), int(coarse.m)], dtype=np.int64
            ),
        }}
    return build


def _pin_level(level: int) -> None:
    """Pin the just-written stream-level snapshot so the deep phase's
    own barriers keep carrying it (the projection maps must survive a
    kill at ANY later barrier)."""
    from ..resilience import checkpoint as ckpt

    mgr = ckpt.active()
    if mgr is not None:
        mgr.pin([f"stream-level-{level}"])


def _section(levels_meta: List[dict], cmaps, graph, handoff_n: int,
             handoff_m: int, streamed: int, resumed: int, k: int) -> dict:
    """The run report's schema-v9 ``external`` section."""
    from ..resilience import memory as memory_mod

    n, m = _sizes(graph)
    n_pad, m_pad, _ = memory_mod.padded_bucket(n, m, k)
    fine_csr = memory_mod.device_csr_bytes(n_pad, m_pad)
    decode_s = sum(lv.get("decode_s", 0.0) for lv in levels_meta)
    drain_s = sum(lv.get("drain_s", 0.0) for lv in levels_meta)
    return {
        "enabled": True,
        "levels": levels_meta,
        "streamed_levels": streamed,
        "resumed_levels": resumed,
        "chunks_total": sum(lv.get("chunks", 0) for lv in levels_meta),
        "decoded_bytes": sum(
            lv.get("decoded_bytes", 0) for lv in levels_meta
        ),
        "uploaded_bytes": sum(
            lv.get("uploaded_bytes", 0) for lv in levels_meta
        ),
        "spilled_bytes": sum(
            lv.get("spilled_bytes", 0) for lv in levels_meta
        ),
        "overlap_frac": _overlap(decode_s, drain_s),
        # 0 whenever >= 1 level streamed: the fine CSR never lands on
        # the device (only chunk buffers + the O(n) vectors do); the
        # in-core cost it avoided is reported next to it
        "fine_device_resident_bytes": 0 if streamed > 0 else fine_csr,
        "fine_csr_bytes": fine_csr,
        "handoff": {"n": handoff_n, "m": handoff_m,
                    "estimate_bytes": memory_mod.estimate_run_bytes(
                        handoff_n, handoff_m, k)},
    }


def external_partition(graph, ctx, facade=None) -> np.ndarray:
    """Functional entry for the memory ladder's rung-3 reroute
    (resilience/memory.py): run the streaming subsystem over whatever
    graph the ladder holds (host CSR, compressed, or spec wrapper).
    ``facade`` is accepted for signature parity with the legacy
    ``semi_external_partition`` it replaces as rung 3's primary."""
    del facade
    return ExternalPartitioner(ctx).partition(graph)
