"""Device-streamed coarsening: bulk-sync LP rounds + chunked contraction.

The out-of-core inversion of ops/lp.py + ops/contraction.py: instead of
the graph living on device and the kernels sweeping it whole, only the
**label / cluster-weight / node-weight vectors** (O(n)) are
device-resident and the edge list streams through one fixed-shape padded
chunk buffer.  Per LP round:

  1. for each chunk (async-dispatched, so chunk ``i+1``'s host decode
     overlaps chunk ``i``'s device compute): gather the round-start
     labels of the chunk's neighbors, aggregate per-(row, label)
     connection weights (``ops.segments.aggregate_by_key`` — exact,
     because node-range chunks hold complete rows), argmax per row with
     hashed tie-breaking and a cluster-weight-cap feasibility mask, and
     scatter the per-node *wanted* label into the round's wish vector;
  2. one global apply: capacity-respecting prefix acceptance per target
     cluster (``accept_prefix_by_capacity``, priority = node id) against
     the ROUND-START weights, then the label/weight vectors update.

Rating against round-start labels + one deterministic global apply is
what makes the result **chunk-count invariant**: any chunking of the
same graph produces bitwise-identical labels (pinned in
tests/test_external.py), so operators can trade chunk size against
overlap freely without forking results.

Contraction streams the same chunks once more: per chunk the device
maps endpoints through the (device-resident) cluster map, deduplicates
with ``aggregate_by_key``, and the host accumulates the deduplicated
coarse COO with periodic re-dedup — peak host memory is
O(coarse m + chunk), the ``resilience/memory._host_contract`` idiom at
device speed.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import caching
from ..dtypes import WEIGHT_DTYPE
from ..ops.segments import (
    accept_prefix_by_capacity,
    aggregate_by_key,
    apply_move_weight_delta,
    argmax_per_segment,
)
from . import chunkstore

#: Clustering is considered stalled when the coarse node count stays
#: above this fraction of the fine count (the semi-external rule).
STALL_FRACTION = 0.95


# ---------------------------------------------------------------------------
# streaming LP
# ---------------------------------------------------------------------------


def make_vectors(store: chunkstore.ChunkStore, node_weights):
    """The device-resident fine-level state: labels (identity), cluster
    weights (node weights), node weights — padded so a chunk-span slice
    starting at any real v0 never clamps (``n_vec >= n + span + 1``)."""
    n = store.n
    n_vec = caching.pad_size(n + store.span + 1, 256)
    labels = jnp.arange(n_vec, dtype=jnp.int32)
    nw = np.zeros(n_vec, dtype=np.dtype(WEIGHT_DTYPE))
    if node_weights is None:
        nw[:n] = 1
    else:
        nw[:n] = np.asarray(node_weights).astype(np.dtype(WEIGHT_DTYPE))
    node_w = jax.device_put(nw)
    cluster_w = node_w  # every node starts as its own cluster
    return labels, cluster_w, node_w


@partial(jax.jit, static_argnames=("span",))
def _chunk_wanted(labels, cluster_w, node_w, wanted, cap,
                  src_local, dst, w, v0, m_real, salt, span: int):
    """One chunk's wish pass: per-row best feasible label vs the
    round-start state, written into the round's wish vector.  Pure
    device work — the driver chains these without a host sync."""
    e_pad = src_local.shape[0]
    valid = jnp.arange(e_pad, dtype=jnp.int32) < m_real
    row = jnp.where(valid, src_local, span).astype(jnp.int32)
    n_vec = labels.shape[0]
    tl = jnp.where(valid, labels[jnp.clip(dst, 0, n_vec - 1)], -1)
    w_m = jnp.where(valid, w, 0)
    r_g, t_g, w_g = aggregate_by_key(row, tl, w_m)

    nw_rows = lax.dynamic_slice(node_w, (v0,), (span,))
    mover_w = nw_rows[jnp.clip(r_g, 0, span - 1)]
    t_clip = jnp.clip(t_g, 0, n_vec - 1)
    feasible = (t_g >= 0) & (cluster_w[t_clip] + mover_w <= cap)
    best, _ = argmax_per_segment(
        r_g, t_g, w_g, num_segments=span, tie_salt=salt, feasible=feasible
    )
    cur = lax.dynamic_slice(labels, (v0,), (span,))
    want = jnp.where((best >= 0) & (best != cur), best, -1).astype(jnp.int32)
    return lax.dynamic_update_slice(wanted, want, (v0,))


@jax.jit
def _apply_round(labels, cluster_w, node_w, wanted, cap):
    """The round's global commit: per-target prefix acceptance against
    the round-start headroom (node-id priority — deterministic and
    chunk-count independent), then label/weight updates.  Conservative
    on capacity: departures in the same round free no headroom, so the
    cap is NEVER exceeded (the exactness the rung-3 host LP fix pins)."""
    n_vec = labels.shape[0]
    mover = wanted >= 0
    headroom = jnp.maximum(cap - cluster_w, 0)
    accept = accept_prefix_by_capacity(
        jnp.where(mover, wanted, -1),
        jnp.arange(n_vec, dtype=jnp.int32),
        jnp.where(mover, node_w, 0),
        headroom,
    )
    new_labels = jnp.where(accept, wanted, labels).astype(jnp.int32)
    new_cw = apply_move_weight_delta(
        cluster_w, labels, jnp.where(accept, wanted, labels), accept, node_w
    )
    moved = jnp.sum(accept.astype(jnp.int32))
    return new_labels, new_cw, moved


def stream_lp(store: chunkstore.ChunkStore, labels, cluster_w, node_w,
              cap: int, seed: int, rounds: int):
    """Run up to ``rounds`` streaming LP rounds; returns
    ``(labels, cluster_w, stats)`` with the decode/drain timings the
    overlap accounting needs: the drain (one scalar pull per round) is
    the stream's only host sync, so chunk decodes that ran before it
    overlapped the device's async dispatch queue by construction."""
    cap_dev = jnp.asarray(
        min(int(cap), int(np.iinfo(np.dtype(WEIGHT_DTYPE)).max)),
        dtype=node_w.dtype,
    )
    stats = {"rounds": 0, "moved": 0, "decode_s": 0.0, "drain_s": 0.0}
    for r in range(max(1, int(rounds))):
        wanted = jnp.full(labels.shape[0], -1, dtype=jnp.int32)
        salt = jnp.int32((seed * 7919 + r * 104729) & 0x7FFFFFFF)
        for c in range(store.num_chunks):
            t0 = time.perf_counter()
            src_local, dst, w, v0, m_real = store.upload(c)
            stats["decode_s"] += time.perf_counter() - t0
            wanted = _chunk_wanted(
                labels, cluster_w, node_w, wanted, cap_dev,
                src_local, dst, w, v0, m_real, salt, store.span,
            )
        labels, cluster_w, moved = _apply_round(
            labels, cluster_w, node_w, wanted, cap_dev
        )
        t0 = time.perf_counter()
        moved_i = chunkstore.pull_moved(moved)
        stats["drain_s"] += time.perf_counter() - t0
        stats["rounds"] = r + 1
        stats["moved"] += moved_i
        if moved_i == 0:
            break
    return labels, cluster_w, stats


# ---------------------------------------------------------------------------
# chunked contraction (coarse CSR accumulates host-side)
# ---------------------------------------------------------------------------


@jax.jit
def _chunk_coarse(cmap_dev, src_local, dst, w, v0, m_real):
    """Map one chunk's endpoints through the cluster map and
    deduplicate inter-cluster edges on device; the host pulls only the
    deduplicated groups."""
    e_pad = src_local.shape[0]
    n_vec = cmap_dev.shape[0]
    valid = jnp.arange(e_pad, dtype=jnp.int32) < m_real
    g_src = jnp.clip(v0 + src_local, 0, n_vec - 1)
    cu = jnp.where(valid, cmap_dev[g_src], -1)
    cv = jnp.where(valid, cmap_dev[jnp.clip(dst, 0, n_vec - 1)], -1)
    keep = valid & (cu >= 0) & (cv >= 0) & (cu != cv)
    cu = jnp.where(keep, cu, -1)
    cv = jnp.where(keep, cv, 0)
    w2 = jnp.where(keep, w, 0)
    return aggregate_by_key(cu, cv, w2)


def stream_contract(store: chunkstore.ChunkStore, labels_host: np.ndarray,
                    node_weights) -> Tuple[object, np.ndarray, dict]:
    """Contract the streamed fine graph under ``labels_host``.

    Returns ``(coarse HostGraph, cmap, stats)``.  The coarse COO
    accumulates host-side with periodic re-dedup, so the host high-water
    stays ~O(coarse m + one chunk's groups).  Chunk c's groups are
    absorbed only after chunk c+1 has been dispatched, so the host pull
    overlaps the next chunk's device compute."""
    from ..graphs.host import HostGraph

    n = store.n
    uniq, cmap = np.unique(labels_host[:n], return_inverse=True)
    c_n = int(len(uniq))
    cmap = cmap.astype(np.int64)
    nw = (
        np.ones(n, dtype=np.int64) if node_weights is None
        else np.asarray(node_weights, dtype=np.int64)
    )
    cw = np.zeros(max(c_n, 1), dtype=np.int64)
    np.add.at(cw, cmap, nw)

    # n_vec-padded device cluster map (-1 on pad slots → dropped edges)
    n_vec = caching.pad_size(n + store.span + 1, 256)
    cmap_full = np.full(n_vec, -1, dtype=np.int32)
    cmap_full[:n] = cmap.astype(np.int32)
    cmap_dev = jax.device_put(cmap_full)

    acc_key = np.empty(0, dtype=np.int64)
    acc_w = np.empty(0, dtype=np.int64)

    def dedup(keys, weights):
        uk, inv = np.unique(keys, return_inverse=True)
        uw = np.zeros(len(uk), dtype=np.int64)
        np.add.at(uw, inv, weights)
        return uk, uw

    stats = {"decode_s": 0.0, "drain_s": 0.0}
    pending = None
    for c in range(store.num_chunks):
        t0 = time.perf_counter()
        src_local, dst, w, v0, m_real = store.upload(c)
        stats["decode_s"] += time.perf_counter() - t0
        groups = _chunk_coarse(cmap_dev, src_local, dst, w, v0, m_real)
        if pending is not None:
            acc_key, acc_w = _absorb(
                pending, c_n, acc_key, acc_w, dedup, stats
            )
        pending = groups
    if pending is not None:
        acc_key, acc_w = _absorb(pending, c_n, acc_key, acc_w, dedup, stats)
    acc_key, acc_w = dedup(acc_key, acc_w)

    cu = (acc_key // max(c_n, 1)).astype(np.int64)
    cv = (acc_key % max(c_n, 1)).astype(np.int32)
    xadj = np.zeros(c_n + 1, dtype=np.int64)
    np.add.at(xadj, cu + 1, 1)
    np.cumsum(xadj, out=xadj)
    coarse = HostGraph(
        xadj=xadj,
        adjncy=cv,
        node_weights=cw[:c_n],
        edge_weights=acc_w if (acc_w != 1).any() else None,
    )
    return coarse, cmap.astype(np.int32), stats


def _absorb(groups, c_n, acc_key, acc_w, dedup, stats):
    """Pull one chunk's deduplicated groups (a host sync — scheduled
    after the NEXT chunk's dispatch so it overlaps device compute) and
    fold them into the accumulator."""
    t0 = time.perf_counter()
    cu, cv, w = chunkstore.pull_coarse_groups(*groups)
    stats["drain_s"] += time.perf_counter() - t0
    key = cu * np.int64(max(c_n, 1)) + cv
    acc_key = np.concatenate([acc_key, key])
    acc_w = np.concatenate([acc_w, w])
    if len(acc_key) > 4 * max(len(key), 1 << 20):
        acc_key, acc_w = dedup(acc_key, acc_w)
    return acc_key, acc_w
