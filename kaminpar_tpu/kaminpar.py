"""Public API facade.

Analog of class KaMinPar (include/kaminpar-shm/kaminpar.h:783-976,
kaminpar-shm/kaminpar.cc:297-463): builder-style — construct with a context,
set a graph, then compute partitions with k / epsilon / explicit block
weights.  Handles the same preprocessing as the reference: isolated-node
removal and reintegration (kaminpar.cc:392-431) and permutation-aware output
copy (kaminpar.cc:437-448).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

from .context import Context, PartitioningMode
from .graphs.host import (
    HostGraph,
    count_isolated_nodes,
    remove_isolated_nodes,
    validate as validate_graph,
)
from .presets import create_context_by_preset_name
from .utils import rng as rng_mod
from .utils import timer
from .utils.logger import OutputLevel, log, set_output_level


class KaMinPar:
    """TPU-native k-way graph partitioner with the reference's builder API.

    Usage (mirrors bindings/python/src/kaminpar/__init__.py):
        ctx = kaminpar_tpu.context_from_preset("default")
        partitioner = KaMinPar(ctx)
        partitioner.set_graph(graph)
        part = partitioner.compute_partition(k=16, epsilon=0.03)
    """

    def __init__(self, ctx: Union[Context, str, None] = None):
        if ctx is None:
            ctx = create_context_by_preset_name("default")
        elif isinstance(ctx, str):
            ctx = create_context_by_preset_name(ctx)
        self.ctx = ctx
        self._graph: Optional[HostGraph] = None
        self.output_level = OutputLevel.APPLICATION
        # set by compute_partition when a run wound down early under a
        # deadline/preemption (resilience/deadline.py); None otherwise
        self.last_anytime: Optional[dict] = None
        # warm-start state (dynamic repartitioning, dynamic/): a valid
        # full-k partition that seeds the v-cycle scheme instead of the
        # initial deep run; one-shot — consumed by the next
        # compute_partition call and cleared afterwards
        self._warm_part: Optional[np.ndarray] = None
        self._warm_levels: Optional[int] = None

    # -- graph ingestion (KaMinPar::borrow_and_mutate_graph / copy_graph) --
    def set_graph(self, graph, validate: bool = False) -> "KaMinPar":
        """Accepts a HostGraph or a CompressedHostGraph (terapart mode).
        With ctx.compression.enabled, plain graphs are stored compressed
        (the Graph facade's CSR/compressed dispatch analog,
        kaminpar-shm/datastructures/graph.h:24-62)."""
        from .external.chunkstore import StreamedSpecGraph
        from .graphs.compressed import CompressedHostGraph, compress_host_graph

        from .utils.assertions import heavy_assertions_enabled

        if isinstance(graph, (CompressedHostGraph, StreamedSpecGraph)):
            # compressed containers and generator-spec wrappers pass
            # through: their consumers stream (decode_range / chunk
            # regeneration) instead of reading a flat CSR
            self._graph = graph
        else:
            # heavy assertion level always validates, mirroring the
            # KASSERT(validate_graph(...), assert::heavy) call in
            # kaminpar-shm/kaminpar.cc:176
            if validate or heavy_assertions_enabled():
                validate_graph(graph)
            if self.ctx.compression.enabled:
                graph = compress_host_graph(graph)
            self._graph = graph
        return self

    def copy_graph(
        self,
        xadj: Sequence[int],
        adjncy: Sequence[int],
        vwgt: Optional[Sequence[int]] = None,
        adjwgt: Optional[Sequence[int]] = None,
    ) -> "KaMinPar":
        """CSR ingestion (KaMinPar::copy_graph signature)."""
        self._graph = HostGraph(
            xadj=np.asarray(xadj),
            adjncy=np.asarray(adjncy, dtype=np.int32),
            node_weights=None if vwgt is None else np.asarray(vwgt),
            edge_weights=None if adjwgt is None else np.asarray(adjwgt),
        )
        return self

    def set_output_level(self, level: OutputLevel) -> "KaMinPar":
        """Instance-scoped (kaminpar.h set_output_level): applied to the
        process-global logger only for the duration of compute_partition,
        so a QUIET instance does not mute the embedding process.  When
        never called, the global level is left untouched."""
        self.output_level = OutputLevel(level)
        self._explicit_level = self.output_level
        return self

    def graph(self) -> Optional[HostGraph]:
        return self._graph

    def set_initial_partition(
        self, partition, max_levels: Optional[int] = None
    ) -> "KaMinPar":
        """Warm-start the next ``compute_partition`` call (v-cycle
        scheme only): ``partition`` must be a valid full-k labeling of
        the current graph; the v-cycle driver refines it instead of
        running the initial deep multilevel pass.  ``max_levels`` bounds
        the warm cycle's restricted-coarsening depth (0 = refinement
        only).  One-shot: cleared when the call returns."""
        self._warm_part = (
            None if partition is None
            else np.asarray(partition, dtype=np.int32)
        )
        self._warm_levels = max_levels
        return self

    # -- main entry point (KaMinPar::compute_partition, kaminpar.cc:297) --
    def compute_partition(
        self,
        k: Optional[int] = None,
        epsilon: Optional[float] = None,
        max_block_weights: Optional[np.ndarray] = None,
        min_block_weights: Optional[np.ndarray] = None,
        seed: Optional[int] = None,
    ) -> np.ndarray:
        if self._graph is None:
            raise RuntimeError("no graph set; call set_graph() first")
        from .graphs.compressed import CompressedHostGraph
        from .ops.lane_gather import clear_plan_cache

        # previous runs' routed-gather plans pin O(m) device memory and
        # belong to freed graphs — drop them before building new levels
        clear_plan_cache()
        graph = self._graph
        if isinstance(graph, CompressedHostGraph) and self._must_decode(
            graph
        ):
            graph = self._decode_cached(graph)
        # else: the graph STAYS compressed — the deep pipeline streams
        # the device upload chunk-by-chunk (TeraPart compute parity:
        # peak host memory is compressed + one chunk + O(n); see
        # graphs/csr.device_graph_from_compressed) and the RESULT
        # metrics stream the same way
        ctx = self.ctx
        if seed is not None:
            ctx.seed = int(seed)
        rng_mod.set_seed(ctx.seed)

        ctx.partition.setup(
            graph,
            k=k,
            epsilon=epsilon,
            max_block_weights=max_block_weights,
        )
        if min_block_weights is not None:
            ctx.partition.min_block_weights = np.asarray(
                min_block_weights, dtype=np.int64
            )
        self._validate_parameters()
        k = ctx.partition.k

        from . import telemetry
        from .utils import heap_profiler, statistics
        from .utils.heap_profiler import scoped_heap_profiler

        timer.GLOBAL_TIMER.reset()
        heap_profiler.reset()
        statistics.reset()
        # telemetry shares the timer's nesting caveat: when this run is
        # embedded in another pipeline (shm IP inside the dist driver),
        # the outer run owns the stream and its annotations
        owns_stream = timer.GLOBAL_TIMER.idle()
        if owns_stream:
            telemetry.reset()
            telemetry.annotate(
                preset=ctx.preset_name,
                seed=int(ctx.seed),
                k=int(k),
                epsilon=float(ctx.partition.epsilon),
                mode=ctx.partitioning.mode.value,
                graph={"n": int(graph.n), "m": int(graph.m)},
            )
        from .partitioning import debug
        from .utils.logger import output_level as global_output_level

        # preemption safety: the run that OWNS the stream (same idle-timer
        # guard as the telemetry annotations) may arm a deadline budget
        # and a checkpoint manager; nested IP runs inside the dist driver
        # never do — a checkpoint must not record an inner pipeline's
        # stage as the outer run's.
        from .resilience import checkpoint as ckpt_mod
        from .resilience import deadline as deadline_mod
        from .resilience import memory as mem_mod

        mgr = None
        res_ctx = ctx.resilience
        self.last_anytime = None  # stale verdicts must not survive a rerun
        # hard wall-clock watchdog (resilience/supervisor.py): the
        # cooperative budget above is checked BETWEEN launches and can
        # never interrupt a hung one; when a hard ceiling resolves
        # (env override, or factor x budget for budgeted runs) the
        # partitioning block below runs under an armed watchdog stage
        # that converts a wall-clock overrun into a structured,
        # breaker-relevant StageHang.  None = no ceiling = no-op guard.
        from .resilience import supervisor as sup_mod

        hard_ceiling_s = None
        if owns_stream:
            # self-heal leftover state from an exceptional unwind of a
            # previous run in this process (a stale manager or deadline
            # must not govern this run), arm the configured budget while
            # PRESERVING a preemption signal that arrived before the run
            # (deadline.begin_run), and build/validate the checkpoint
            # manager (create_manager: mismatch/corruption degrade to a
            # logged clean restart)
            ckpt_mod.deactivate()
            deadline_mod.begin_run(
                res_ctx.time_budget or None, res_ctx.budget_grace,
                getattr(res_ctx, "hard_deadline_factor", None),
            )
            mgr = ckpt_mod.create_manager(res_ctx, self._graph, ctx)
            if mgr is not None:
                ckpt_mod.activate(mgr)
            # memory governor: price this run against the declared
            # budget and pick the starting ladder rung (after
            # begin_run's fresh RunState — the governor state rides on
            # it); dormant without a budget, but the ladder below still
            # catches any DeviceOOM
            mem_mod.begin_run(graph, ctx)
            hard_ceiling_s = sup_mod.hard_ceiling(
                res_ctx.time_budget, res_ctx.budget_grace,
                getattr(res_ctx, "hard_deadline_factor", None),
            )
        if not owns_stream:
            # nested run (shm IP inside the dist driver): blind the
            # barrier hook for the duration — inner drivers must neither
            # rewrite the outer run's manifest with their own stage nor
            # consume its resume state (unsuspended in the finally below)
            ckpt_mod.suspend()

        debug.dump_toplevel_graph(ctx, graph)
        # the logger is process-global; apply this instance's level only
        # for the duration of the computation
        prior_level = global_output_level()
        try:
            set_output_level(getattr(self, "_explicit_level", prior_level))
            if self.output_level >= OutputLevel.APPLICATION:
                self._print_context_summary(graph, ctx)
            with sup_mod.stage_guard(
                "partition", hard_ceiling_s
            ), timer.scoped_timer("partitioning"), scoped_heap_profiler(
                "partitioning"
            ):
                # isolated-node preprocessing (kaminpar.cc:392-404)
                from .external.chunkstore import StreamedSpecGraph

                num_isolated = count_isolated_nodes(graph)
                still_compressed = isinstance(graph, CompressedHostGraph)
                # generator-spec wrappers keep isolated nodes in the
                # stream: extraction would materialize the adjacency,
                # and the external scheme's device phases (LP packing +
                # balancers) place edge-less nodes anyway
                streaming_src = isinstance(graph, StreamedSpecGraph)
                resumed_result = (
                    mgr.take_result_resume() if mgr is not None else None
                )
                if (
                    resumed_result is not None
                    and resumed_result.shape == (graph.n,)
                ):
                    # a run preempted AFTER its output gate left a final
                    # `result` snapshot: nothing to recompute
                    partition = resumed_result
                elif (
                    num_isolated
                    and graph.n > num_isolated
                    and still_compressed
                ):
                    # compressed twin of the decoded branch below: the
                    # core graph is extracted compressed-to-compressed
                    # (chunk-streamed re-encode, graphs/compressed.py)
                    # and isolated nodes refill blocks by headroom —
                    # skipping this cost 28% cut at k=128 (isolated
                    # weight distorts coarsening and balance)
                    from .graphs.compressed import extract_core_compressed
                    from .graphs.host import NodePermutation

                    core_cg, core_ids, iso_ids = extract_core_compressed(
                        graph
                    )
                    part_core = self._partition_core_governed(core_cg, ctx)
                    new_to_old = np.concatenate([core_ids, iso_ids])
                    old_to_new = np.empty(graph.n, dtype=np.int64)
                    old_to_new[new_to_old] = np.arange(graph.n)
                    partition = self._reintegrate_isolated(
                        graph, core_cg,
                        NodePermutation(old_to_new, new_to_old),
                        num_isolated, part_core,
                    )
                elif (
                    num_isolated
                    and graph.n > num_isolated
                    and not still_compressed
                    and not streaming_src
                ):
                    core, perm, _ = remove_isolated_nodes(graph)
                    core_ctx = ctx  # weights already set up from the full graph
                    if self._warm_part is not None:
                        # warm seed follows the core permutation (the
                        # first core.n permuted slots are the connected
                        # nodes the core run partitions)
                        self._warm_part = self._warm_part[
                            perm.new_to_old[: core.n]
                        ]
                    part_core = self._partition_core_governed(core, core_ctx)
                    partition = self._reintegrate_isolated(
                        graph, core, perm, num_isolated, part_core
                    )
                elif num_isolated == graph.n and graph.n > 0:
                    partition = self._partition_only_isolated(graph)
                else:
                    partition = self._partition_core_governed(graph, ctx)
        finally:
            set_output_level(prior_level)
            # warm-start state is one-shot: a later call on this
            # instance (different graph, different k) must never
            # silently inherit it
            self._warm_part = None
            self._warm_levels = None
            if not owns_stream:
                ckpt_mod.unsuspend()

        # strict-balance output gate (resilience/gate.py): validate the
        # partition invariants host-side and repair balance violations,
        # so the postcondition below holds no matter which optional fast
        # paths degraded during the run.  Only a run that OWNS the
        # telemetry stream (idle timer — same guard as the annotations
        # above) may stamp its verdict into the report; nested IP runs
        # inside the dist driver still gate, but anonymously.
        from .resilience import gate as output_gate

        if output_gate.gate_enabled() and ctx.resilience.output_gate:
            with timer.scoped_timer("output-gate"):
                partition = output_gate.apply(
                    self, graph, partition, ctx, annotate=owns_stream
                )

        # final barrier: a `result` snapshot AFTER the gate, so a
        # preemption between here and the caller resumes instantly; then
        # stamp the anytime/checkpoint sections into the run report and
        # release the run-scoped preemption state
        if owns_stream:
            if mgr is not None and mgr.enabled:
                final_part = partition
                ckpt_mod.barrier(
                    "result", scheme="facade",
                    payload=lambda: {"state": {
                        "partition": np.asarray(final_part, dtype=np.int32)
                    }},
                )
            if deadline_mod.triggered():
                self.last_anytime = deadline_mod.state()
                telemetry.annotate(anytime=self.last_anytime)
                from .utils.logger import log_warning

                # .get(): a driverless path (e.g. the all-isolated-nodes
                # branch) crosses no barrier, so stage/reason may be absent
                log_warning(
                    "ANYTIME result: wound down at stage "
                    f"'{self.last_anytime.get('stage') or 'start'}' "
                    f"({self.last_anytime.get('reason')}); partition is "
                    "gate-validated but lower-effort"
                )
            else:
                self.last_anytime = None
            if mgr is not None:
                telemetry.annotate(checkpoint=mgr.summary())
            # memory-budget audit trail: annotate only when a budget was
            # declared or the ladder/pressure hook engaged — the report
            # builder fills the well-formed disabled default otherwise
            mem_summary = mem_mod.summary()
            if mem_summary.get("enabled"):
                telemetry.annotate(memory_budget=mem_summary)
            ckpt_mod.deactivate()

        debug.dump_toplevel_partition(ctx, partition)
        from .utils.assertions import AssertionLevel, kassert

        kassert(
            lambda: partition.shape == (graph.n,)
            and (partition >= 0).all()
            and (partition < k).all(),
            "partition labels out of range (validate_partition analog)",
            AssertionLevel.LIGHT,
        )
        # telemetry only needs the metrics when this run owns the stream
        # (idle-gated, like the annotation itself): nested IP runs inside
        # the dist driver would otherwise pay an O(n + m) pass per
        # candidate and discard the result
        if self.output_level >= OutputLevel.APPLICATION or (
            telemetry.enabled() and timer.GLOBAL_TIMER.idle()
        ):
            self._print_result(graph, partition)
        return partition

    def _decode_cached(self, cgraph):
        """Memoized full decode of a compressed input: repeated
        compute_partition calls (seed/k sweeps) and the compressed-stream
        degradation fallback shouldn't re-pay the O(m) decompression."""
        cached = getattr(self, "_decoded", None)
        if cached is None or cached[0] is not cgraph:
            self._decoded = (cgraph, cgraph.decode())
        return self._decoded[1]

    def _partition_core_governed(self, graph, ctx: Context) -> np.ndarray:
        """The core partition under the memory governor's OOM recovery
        ladder (resilience/memory.py): a classified DeviceOOM anywhere
        below retries at progressively more frugal rungs (tight pads,
        host-spilled hierarchy, semi-external streaming, host-only)
        instead of surfacing RESOURCE_EXHAUSTED.  A plain try-through
        when the governor is dormant and nothing OOMs."""
        from .resilience import integrity as integrity_mod
        from .resilience import memory as mem_mod

        # corruption-recovery ladder OUTSIDE the OOM ladder: a sentinel
        # violation (silent data corruption detected at a phase boundary)
        # re-executes once from the last clean checkpoint barrier; a
        # second violation is the `corrupt-result` verdict and propagates
        return integrity_mod.run_with_retry(
            lambda: mem_mod.run_ladder(
                lambda: self._partition_core_resilient(graph, ctx),
                graph, ctx, facade=self,
            ),
            where="partition-core",
        )

    def _partition_core_resilient(self, graph, ctx: Context) -> np.ndarray:
        """_partition_core under the compressed-stream degradation
        contract: when the chunk-streamed device upload of a compressed
        graph fails (device OOM, injected fault), decode to the plain
        host CSR and re-partition — TeraPart memory parity degrades to
        correctness-first instead of aborting the run."""
        from .graphs.compressed import CompressedHostGraph

        if not isinstance(graph, CompressedHostGraph):
            return self._partition_core(graph, ctx)
        from .resilience import with_fallback

        return with_fallback(
            lambda: self._partition_core(graph, ctx),
            lambda exc: self._partition_core(
                self._decode_cached(graph), ctx
            ),
            site="compressed-stream",
        )

    # -- scheme dispatch (factories.cc:40-57 create_partitioner) --
    def _partition_core(self, graph: HostGraph, ctx: Context) -> np.ndarray:
        mode = ctx.partitioning.mode
        if mode == PartitioningMode.KWAY:
            from .partitioning.kway import KWayMultilevelPartitioner

            return KWayMultilevelPartitioner(ctx).partition(graph)
        elif mode == PartitioningMode.DEEP:
            from .partitioning.deep import DeepMultilevelPartitioner

            return DeepMultilevelPartitioner(ctx).partition(graph)
        elif mode == PartitioningMode.RB:
            from .partitioning.rb_scheme import RBMultilevelPartitioner

            return RBMultilevelPartitioner(ctx).partition(graph)
        elif mode == PartitioningMode.VCYCLE:
            from .partitioning.vcycle import VcycleDeepMultilevelPartitioner

            return VcycleDeepMultilevelPartitioner(
                ctx,
                initial_partition=self._warm_part,
                max_levels=self._warm_levels,
            ).partition(graph)
        elif mode == PartitioningMode.EXTERNAL:
            from .external.driver import ExternalPartitioner

            return ExternalPartitioner(ctx).partition(graph)
        raise ValueError(f"unknown partitioning mode: {mode}")

    def _validate_parameters(self) -> None:
        """KaMinPar::validate_partition_parameters (kaminpar.cc:465)."""
        p = self.ctx.partition
        if p.k < 1:
            raise ValueError("k must be >= 1")
        if int(p.max_block_weights.sum()) < p.total_node_weight:
            raise ValueError(
                "infeasible: total max block weight "
                f"{int(p.max_block_weights.sum())} < total node weight "
                f"{p.total_node_weight}"
            )

    def _reintegrate_isolated(
        self, graph, core, perm, num_isolated, part_core
    ) -> np.ndarray:
        """kaminpar.cc:422-431: isolated nodes fill up underloaded blocks."""
        p = self.ctx.partition
        partition = np.zeros(graph.n, dtype=np.int32)
        core_n = core.n
        # nodes permuted: first core_n slots are connected nodes
        partition_permuted = np.zeros(graph.n, dtype=np.int32)
        partition_permuted[:core_n] = part_core

        node_w = graph.node_weight_array()[perm.new_to_old]
        bw = np.zeros(p.k, dtype=np.int64)
        np.add.at(bw, part_core, node_w[:core_n].astype(np.int64))
        partition_permuted[core_n:] = _fill_blocks_by_headroom(
            node_w[core_n:], bw, p.max_block_weights
        )
        partition[perm.new_to_old] = partition_permuted
        return partition

    def _partition_only_isolated(self, graph) -> np.ndarray:
        """Graph with no edges: fill blocks by headroom under the caps."""
        p = self.ctx.partition
        node_w = graph.node_weight_array()
        bw = np.zeros(p.k, dtype=np.int64)
        return _fill_blocks_by_headroom(node_w, bw, p.max_block_weights)

    def _print_context_summary(self, graph, ctx: Context) -> None:
        """Startup banner + compact context block (the analog of the
        reference's version banner and context printer,
        kaminpar-shm/context.cc / kaminpar-common console_io)."""
        from . import __version__

        p = ctx.partition
        log(f"kaminpar-tpu v{__version__} (preset '{ctx.preset_name}', "
            f"seed {ctx.seed})")
        log(f"  graph: n={graph.n} m={graph.m} "
            f"total_node_weight={graph.total_node_weight}")
        log(f"  partition: k={p.k} eps={p.epsilon} "
            f"mode={ctx.partitioning.mode.value}")
        log(f"  coarsening: {ctx.coarsening.algorithm.value} "
            f"(contraction limit {ctx.coarsening.contraction_limit}), "
            f"refinement: "
            f"{';'.join(a.value for a in ctx.refinement.algorithms)}")

    def _must_decode(self, cgraph) -> bool:
        """Whether a compressed input still needs the full host CSR.

        The streamed-compute path (deep multilevel; chunked device
        upload + chunked RESULT metrics) covers the TeraPart workload;
        host-CSR consumers force a decode: isolated-node pre/processing
        (kaminpar.cc:392-404 walks host rows), non-deep schemes, and
        debug graph dumps."""
        from .context import PartitioningMode

        d = self.ctx.debug
        if (
            d.dump_toplevel_graph
            or d.dump_toplevel_partition
            or d.dump_graph_hierarchy
        ):
            return True
        if self.ctx.partitioning.mode not in (
            PartitioningMode.DEEP,
            # the external scheme is BUILT on never materializing: the
            # chunk store decodes node ranges on demand
            PartitioningMode.EXTERNAL,
        ):
            return True
        # isolated nodes do NOT force a decode: the host-side isolated
        # extraction (kaminpar.cc:392-404) is skipped for compressed
        # inputs and the device pipeline places them instead (LP's
        # isolated-node packing + balancers) — they cut nothing either way
        return False

    def result_metrics(self, graph, partition) -> dict:
        """cut / imbalance / feasible of a computed partition (the RESULT
        line's numbers, also the run report's `result` section).

        Memoized by (graph, partition) identity: the output gate needs
        the driver-path cut for its cross-check and the RESULT printer
        needs the same numbers moments later — without the memo every
        gated call would pay the O(n + m) host sweep twice (and re-
        stream the whole compressed adjacency on TeraPart inputs)."""
        cached = getattr(self, "_metrics_memo", None)
        if (
            cached is not None
            and cached[0] is graph
            and cached[1] is partition
        ):
            return cached[2]
        from .external.chunkstore import (
            StreamedSpecGraph,
            streamed_partition_metrics,
        )
        from .graphs.compressed import (
            CompressedHostGraph,
            compressed_partition_metrics,
        )
        from .graphs.host import host_partition_metrics

        p = self.ctx.partition
        if isinstance(graph, CompressedHostGraph):
            m = compressed_partition_metrics(graph, partition, p.k)
        elif isinstance(graph, StreamedSpecGraph):
            m = streamed_partition_metrics(graph, partition, p.k)
        else:
            m = host_partition_metrics(graph, partition, p.k)
        result = {
            "cut": int(m["cut"]),
            "imbalance": float(m["imbalance"]),
            "feasible": bool(
                (m["block_weights"] <= p.max_block_weights).all()
            ),
        }
        self._metrics_memo = (graph, partition, result)
        return result

    def _print_result(self, graph, partition) -> None:
        """Parseable RESULT line (kaminpar-shm/kaminpar.cc:48) + the
        telemetry result annotation consumed by --report-json."""
        from . import telemetry

        m = self.result_metrics(graph, partition)
        if timer.GLOBAL_TIMER.idle():  # nested runs don't own the stream
            telemetry.annotate(result=m)
        if self.output_level >= OutputLevel.APPLICATION:
            log(
                f"RESULT cut={m['cut']} imbalance={m['imbalance']:.6f} "
                f"feasible={int(m['feasible'])} k={self.ctx.partition.k}"
            )


def _fill_blocks_by_headroom(
    node_w: np.ndarray, block_w: np.ndarray, max_block_weights: np.ndarray
) -> np.ndarray:
    """Assign edge-less (interchangeable) nodes to blocks without exceeding
    the caps: fill blocks in descending-headroom order with node prefixes by
    cumulative weight — O((n + k) log k) instead of a per-node argmax loop
    (kaminpar.cc:422-431 reintegration semantics)."""
    n = len(node_w)
    out = np.zeros(n, dtype=np.int32)
    if n == 0:
        return out
    headroom = (np.asarray(max_block_weights, dtype=np.int64) - block_w).clip(0)
    order = np.argsort(-headroom, kind="stable")
    cum = np.cumsum(node_w.astype(np.int64))
    start = 0
    assigned = 0
    for b in order:
        if start >= n:
            break
        end = int(np.searchsorted(cum, assigned + headroom[b], side="right"))
        out[start:end] = b
        if end > start:
            assigned = int(cum[end - 1])
        start = end
    if start < n:
        # caps cannot hold everything (validated earlier to be impossible
        # for feasible instances); spill into the biggest block
        out[start:] = int(order[0])
    return out


def context_from_preset(name: str) -> Context:
    return create_context_by_preset_name(name)
