"""External-framework bindings.

The reference ships pybind11 Python bindings and a NetworKit Cython module
(bindings/python, bindings/networkit).  This framework *is* Python, so the
"Python binding" is the package itself (kaminpar_tpu.KaMinPar); this
subpackage holds adapters to other graph frameworks:

  * networkit — NetworKit graph -> HostGraph adapter with the reference
    binding's call surface (kaminpar_networkit.cc analog)
  * the C ABI lives in kaminpar_tpu/native/ckaminpar.cpp +
    include/ckaminpar_tpu.h (ckaminpar.h analog)
"""

from .networkit import NetworKitKaMinPar  # noqa: F401
