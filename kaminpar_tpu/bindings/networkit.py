"""NetworKit adapter (bindings/networkit analog).

The reference exposes `kaminpar.KaMinPar(nk_graph).computePartitionWith
Epsilon(k, eps)` through a Cython shim over a NetworKitGraphAdapter
(bindings/networkit/src/kaminpar_networkit.cc).  This module provides the
same surface: a NetworKit graph is converted to a HostGraph (edge weights
rounded to int, NetworKit's default weight 1.0 preserved exactly) and
partitioned by the standard pipeline.  NetworKit itself is an optional
dependency — only the constructor touches it.
"""

from __future__ import annotations

import numpy as np

from ..graphs.host import HostGraph, from_edge_list


def networkit_to_host(nk_graph) -> HostGraph:
    """Convert a networkit.Graph (undirected) to a HostGraph.

    Duck-typed on the NetworKit graph interface (numberOfNodes /
    isDirected / isWeighted / iterEdges / weight), so it needs no import
    of networkit itself."""
    if nk_graph.isDirected():
        raise ValueError("only undirected NetworKit graphs are supported")
    n = nk_graph.numberOfNodes()
    us, vs, ws = [], [], []
    weighted = nk_graph.isWeighted()
    for u, v in nk_graph.iterEdges():
        us.append(u)
        vs.append(v)
        if weighted:
            ws.append(nk_graph.weight(u, v))
    edges = np.stack(
        [np.asarray(us, np.int64), np.asarray(vs, np.int64)], axis=1
    ) if us else np.zeros((0, 2), np.int64)
    ew = None
    if weighted and ws:
        ew = np.rint(np.asarray(ws, np.float64)).astype(np.int64)
        if (ew <= 0).any():
            raise ValueError("edge weights must round to positive integers")
    return from_edge_list(n, edges, edge_weights=ew, symmetrize=True)


class NetworKitKaMinPar:
    """Binding surface of the reference's NetworKit module:
    `KaMinPar(nk_graph).computePartitionWithEpsilon(k, eps)`."""

    def __init__(self, nk_graph, preset: str = "default", seed: int = 0):
        self._host = networkit_to_host(nk_graph)
        self._preset = preset
        self._seed = seed

    def computePartition(self, k: int) -> np.ndarray:
        return self.computePartitionWithEpsilon(k, 0.03)

    def computePartitionWithEpsilon(self, k: int, epsilon: float) -> np.ndarray:
        from ..kaminpar import KaMinPar

        return (
            KaMinPar(self._preset)
            .set_graph(self._host)
            .compute_partition(k=int(k), epsilon=float(epsilon), seed=self._seed)
        )
