"""Python side of the C ABI (kaminpar-shm/ckaminpar.cc analog).

Called by the embedded interpreter inside kaminpar_tpu/native/ckaminpar.cpp:
raw CSR pointers from the C caller are wrapped as numpy arrays *without
copying*, the standard pipeline runs, and the partition is written straight
into the caller's output buffer.
"""

from __future__ import annotations

import ctypes

import numpy as np


def _as_array(ptr: int, dtype, count: int):
    if ptr == 0 or count == 0:
        return None
    ct = ctypes.POINTER(ctypes.c_int64 if dtype == np.int64 else ctypes.c_int32)
    return np.ctypeslib.as_array(ctypes.cast(ptr, ct), shape=(count,))


def compute_from_pointers(
    n: int,
    xadj_ptr: int,
    adjncy_ptr: int,
    vwgt_ptr: int,
    adjwgt_ptr: int,
    out_ptr: int,
    k: int,
    epsilon: float,
    seed: int,
    preset: str,
) -> int:
    """Partition the CSR graph at the given addresses; returns the cut."""
    # The embedded interpreter must never eagerly discover backends: honor
    # JAX_PLATFORMS / KAMINPAR_TPU_PLATFORM before anything imports jax, so
    # a down TPU tunnel cannot hang a C consumer (round-5 verdict Weak #2).
    from .utils import platform as _platform

    _platform.ensure_platform_env()

    from .graphs.host import HostGraph
    from .kaminpar import KaMinPar

    xadj = _as_array(xadj_ptr, np.int64, n + 1)
    if xadj is None:
        xadj = np.zeros(1, dtype=np.int64)
    m = int(xadj[n]) if n > 0 else 0
    adjncy = _as_array(adjncy_ptr, np.int32, m)
    if adjncy is None:
        adjncy = np.zeros(0, dtype=np.int32)
    vwgt = _as_array(vwgt_ptr, np.int32, n)
    adjwgt = _as_array(adjwgt_ptr, np.int32, m)

    graph = HostGraph(
        xadj=np.asarray(xadj, dtype=np.int64).copy(),
        adjncy=np.asarray(adjncy, dtype=np.int32).copy(),
        node_weights=None if vwgt is None else np.asarray(vwgt, np.int64).copy(),
        edge_weights=None if adjwgt is None else np.asarray(adjwgt, np.int64).copy(),
    )
    part = (
        KaMinPar(preset)
        .set_graph(graph)
        .compute_partition(k=int(k), epsilon=float(epsilon), seed=int(seed))
    )
    out = _as_array(out_ptr, np.int32, n)
    if out is not None:
        out[:] = np.asarray(part, dtype=np.int32)[:n]

    src = graph.edge_sources()
    ew = graph.edge_weight_array()
    cut = int(((part[src] != part[graph.adjncy]) * ew).sum()) // 2
    return cut
