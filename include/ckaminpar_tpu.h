/* C API for the TPU-native KaMinPar framework.
 *
 * Parity surface for the reference's C wrapper (kaminpar-shm/ckaminpar.h):
 * a C program hands in a CSR graph and receives a k-way partition.  The
 * implementation (kaminpar_tpu/native/ckaminpar.cpp) embeds a Python
 * interpreter and drives the same pipeline as the Python API, so C callers
 * get the identical partitioner (device-accelerated when a TPU backend is
 * available in the embedded runtime).
 *
 * Usage:
 *   kmp_partitioner *p = kmp_create("default", 0);
 *   int32_t *part = malloc(n * sizeof(int32_t));
 *   int64_t cut = kmp_compute_partition(p, n, xadj, adjncy, NULL, NULL,
 *                                       k, 0.03, part);
 *   if (cut < 0) fprintf(stderr, "%s\n", kmp_last_error(p));
 *   kmp_free(p);
 *
 * Thread-safety: one embedded interpreter per process; calls are
 * serialized on the GIL.  Link against libckaminpar_tpu.so (built by
 * python -m kaminpar_tpu.native.build_capi) and libpython.
 */

#ifndef CKAMINPAR_TPU_H
#define CKAMINPAR_TPU_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct kmp_partitioner kmp_partitioner;

/* Create a partitioner configured by preset name (see
 * kaminpar_tpu.presets; e.g. "default", "fast", "strong", "terapart").
 * `seed` seeds every randomized phase.  Returns NULL on failure. */
kmp_partitioner *kmp_create(const char *preset, int seed);

void kmp_free(kmp_partitioner *p);

/* Partition an undirected CSR graph (METIS convention: both directions of
 * every edge stored) into k blocks with imbalance factor `epsilon`.
 *
 *   n        number of nodes
 *   xadj     int64[n + 1] CSR offsets
 *   adjncy   int32[xadj[n]] neighbor lists
 *   vwgt     int32[n] node weights, or NULL for unit weights
 *   adjwgt   int32[xadj[n]] edge weights, or NULL for unit weights
 *   out      int32[n] receives the block of every node
 *
 * Returns the edge cut (>= 0) or -1 on error (see kmp_last_error). */
int64_t kmp_compute_partition(kmp_partitioner *p, int64_t n,
                              const int64_t *xadj, const int32_t *adjncy,
                              const int32_t *vwgt, const int32_t *adjwgt,
                              int32_t k, double epsilon, int32_t *out);

/* Message for the most recent failure on this partitioner ("" if none).
 * The pointer stays valid until the next call on `p`. */
const char *kmp_last_error(kmp_partitioner *p);

#ifdef __cplusplus
}
#endif

#endif /* CKAMINPAR_TPU_H */
