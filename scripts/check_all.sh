#!/usr/bin/env bash
# The one-command commit gate: tpulint, run-report schema check, a
# chaos smoke run (every fault site injected once; the run must still
# produce a gate-valid partition and a schema-valid report), the
# telemetry.diff regression-gate self-test + BENCH-trend check, a
# preempt-and-resume smoke (SIGTERM an rgg2d run mid-pipeline, resume
# from the checkpoint, assert gate-valid + anytime/checkpoint report
# sections), and the ROADMAP.md tier-1 pytest command.  Exits nonzero
# on the first failing stage.
#
# Usage:  scripts/check_all.sh [--fast]
#         --fast skips the tier-1 pytest stage (lint + schema + chaos
#         smoke + diff self-test; lint + schema are the pair the
#         pre-commit hooks run).
set -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== [1/6] tpulint (vs scripts/tpulint_baseline.json) =="
python -m kaminpar_tpu.lint kaminpar_tpu/ || exit 1

echo "== [2/6] run-report schema (producer selftest, v1/v2 fixtures + v3 producer) =="
python scripts/check_report_schema.py --selftest || exit 1

echo "== [3/6] chaos smoke (KAMINPAR_TPU_FAULTS=all:nth=1) =="
rm -f /tmp/_kmp_chaos_report.json
KAMINPAR_TPU_FAULTS=all:nth=1 python -m kaminpar_tpu \
    "gen:rgg2d;n=4096;avg_degree=8;seed=1" -k 4 \
    --report-json /tmp/_kmp_chaos_report.json || exit 1
python scripts/check_report_schema.py /tmp/_kmp_chaos_report.json || exit 1
python - <<'EOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_chaos_report.json"))
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], f"chaos run failed the gate: {gate}"
assert r["faults"]["plan"] == "all:nth=1", r["faults"]
assert r["progress"], "v2 report carries no progress series"
# a fresh process always backend-compiles, so a zero count here means
# the accounting silently stopped recording, not a warm cache
assert r["compile"]["totals"]["compiles"] > 0, r["compile"]["totals"]
print(f"chaos smoke OK: {len(r['degraded'])} degraded event(s), "
      f"gate valid, cut={gate['cut_recomputed']}, "
      f"{len(r['progress'])} progress series")
EOF

echo "== [4/6] telemetry.diff self-test + BENCH trend =="
# identical reports must pass (rc 0)...
python -m kaminpar_tpu.telemetry.diff \
    /tmp/_kmp_chaos_report.json /tmp/_kmp_chaos_report.json || exit 1
# ...and an injected 50% wall + cut regression must FAIL (rc 1)
python - <<'EOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_chaos_report.json"))
r["result"]["cut"] = int(r["result"]["cut"] * 1.5) + 10
run = r.setdefault("run", {})
run["partition_seconds"] = float(run.get("partition_seconds", 1.0)) * 1.5 + 1.0
json.dump(r, open("/tmp/_kmp_chaos_report_perturbed.json", "w"))
EOF
if python -m kaminpar_tpu.telemetry.diff \
    /tmp/_kmp_chaos_report.json /tmp/_kmp_chaos_report_perturbed.json; then
    echo "ERROR: telemetry.diff accepted an injected 50% regression" >&2
    exit 1
fi
python scripts/bench_trend.py --check || exit 1


echo "== [5/6] preempt-and-resume smoke (SIGTERM mid-run + --resume) =="
CKPT=/tmp/_kmp_ckpt_smoke
rm -rf "$CKPT" /tmp/_kmp_preempt1.json /tmp/_kmp_preempt2.json
python -m kaminpar_tpu "gen:rgg2d;n=65536;avg_degree=8;seed=1" -k 8 \
    --checkpoint-dir "$CKPT" --report-json /tmp/_kmp_preempt1.json -q &
preempt_pid=$!
# signal as soon as the first barrier checkpoint lands => mid-pipeline
for _ in $(seq 1 240); do
    [ -f "$CKPT/manifest.json" ] && break
    sleep 0.5
done
kill -TERM "$preempt_pid" 2>/dev/null
wait "$preempt_pid" || { echo "ERROR: SIGTERM'd run exited nonzero" >&2; exit 1; }
python scripts/check_report_schema.py /tmp/_kmp_preempt1.json || exit 1
python - <<'EOF2' || exit 1
import json
r = json.load(open("/tmp/_kmp_preempt1.json"))
assert r["anytime"]["anytime"] is True, r["anytime"]
assert r["anytime"]["reason"] == "sigterm", r["anytime"]
ck = r["checkpoint"]
assert ck["enabled"] and ck["writes"] > 0 and not ck["memory_only"], ck
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], f"preempted run failed the gate: {gate}"
print(f"preempt OK: anytime at stage {r['anytime'].get('stage')}, "
      f"{ck['writes']} checkpoint write(s)")
EOF2
python -m kaminpar_tpu "gen:rgg2d;n=65536;avg_degree=8;seed=1" -k 8 \
    --checkpoint-dir "$CKPT" --resume --report-json /tmp/_kmp_preempt2.json -q \
    || exit 1
python scripts/check_report_schema.py /tmp/_kmp_preempt2.json || exit 1
python - <<'EOF2' || exit 1
import json
r = json.load(open("/tmp/_kmp_preempt2.json"))
assert r["checkpoint"]["resumed_from"] is not None, r["checkpoint"]
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], f"resumed run failed the gate: {gate}"
print(f"resume OK: resumed from {r['checkpoint']['resumed_from']}, "
      f"cut={gate['cut_recomputed']}")
EOF2

if [ "${1:-}" = "--fast" ]; then
    echo "== [6/6] tier-1 pytest: SKIPPED (--fast) =="
    exit 0
fi

echo "== [6/6] tier-1 pytest (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit $rc
