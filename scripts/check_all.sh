#!/usr/bin/env bash
# The one-command commit gate: tpulint, run-report schema check, and
# the ROADMAP.md tier-1 pytest command.  Exits nonzero on the first
# failing stage.
#
# Usage:  scripts/check_all.sh [--fast]
#         --fast skips the tier-1 pytest stage (lint + schema only,
#         the same pair the pre-commit hooks run).
set -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== [1/3] tpulint (vs scripts/tpulint_baseline.json) =="
python -m kaminpar_tpu.lint kaminpar_tpu/ || exit 1

echo "== [2/3] run-report schema (producer selftest) =="
python scripts/check_report_schema.py --selftest || exit 1

if [ "${1:-}" = "--fast" ]; then
    echo "== [3/3] tier-1 pytest: SKIPPED (--fast) =="
    exit 0
fi

echo "== [3/3] tier-1 pytest (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit $rc
