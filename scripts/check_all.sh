#!/usr/bin/env bash
# The one-command commit gate: tpulint, run-report schema check, a
# chaos smoke run (every fault site injected once; the run must still
# produce a gate-valid partition and a schema-valid report), and the
# ROADMAP.md tier-1 pytest command.  Exits nonzero on the first
# failing stage.
#
# Usage:  scripts/check_all.sh [--fast]
#         --fast skips the tier-1 pytest stage (lint + schema + chaos
#         smoke; lint + schema are the pair the pre-commit hooks run).
set -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== [1/4] tpulint (vs scripts/tpulint_baseline.json) =="
python -m kaminpar_tpu.lint kaminpar_tpu/ || exit 1

echo "== [2/4] run-report schema (producer selftest) =="
python scripts/check_report_schema.py --selftest || exit 1

echo "== [3/4] chaos smoke (KAMINPAR_TPU_FAULTS=all:nth=1) =="
rm -f /tmp/_kmp_chaos_report.json
KAMINPAR_TPU_FAULTS=all:nth=1 python -m kaminpar_tpu \
    "gen:rgg2d;n=4096;avg_degree=8;seed=1" -k 4 \
    --report-json /tmp/_kmp_chaos_report.json || exit 1
python scripts/check_report_schema.py /tmp/_kmp_chaos_report.json || exit 1
python - <<'EOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_chaos_report.json"))
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], f"chaos run failed the gate: {gate}"
assert r["faults"]["plan"] == "all:nth=1", r["faults"]
print(f"chaos smoke OK: {len(r['degraded'])} degraded event(s), "
      f"gate valid, cut={gate['cut_recomputed']}")
EOF

if [ "${1:-}" = "--fast" ]; then
    echo "== [4/4] tier-1 pytest: SKIPPED (--fast) =="
    exit 0
fi

echo "== [4/4] tier-1 pytest (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit $rc
