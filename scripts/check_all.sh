#!/usr/bin/env bash
# The one-command commit gate: tpulint, run-report schema check, a
# chaos smoke run (every fault site injected once; the run must still
# produce a gate-valid partition and a schema-valid report), the
# telemetry.diff regression-gate self-test + BENCH-trend check, a
# preempt-and-resume smoke (SIGTERM an rgg2d run mid-pipeline, resume
# from the checkpoint, assert gate-valid + anytime/checkpoint report
# sections), a serving smoke (16-request batch with one poisoned graph,
# fault injection, a tight per-request deadline, repeated shapes for
# cache hits, and a SIGTERM mid-batch drain — all verdicts in one
# schema-valid report), a supervision smoke (--serve-isolation
# process: one injected worker hang SIGKILLed past its 2 s hard
# ceiling, one injected worker crash, the rest served from recycled
# warm workers, heartbeat mtime advancing throughout — exit 0 with
# exactly those two failed verdicts), a memory-governor smoke
# (artificially small
# budget -> ladder engages, forced rung-2 spill/reload, a serving
# insufficient-memory rejection), an out-of-core streaming smoke
# (--scheme external under a 25%-of-estimate budget -> gate-valid,
# fine level never device-resident, stream events + overlap > 0, and
# a mid-stream kill-and-resume that is CUT-IDENTICAL), a dynamic
# repartition smoke (8-delta chain with one bucket-crossing delta and
# one injected dynamic-apply fault: every step gate-valid, >= 1
# in-place and >= 1 rebuild apply, cut trajectory inside the diff
# gate), a dist
# resilience smoke (SIGTERM a
# mesh run mid-pipeline -> resume is CUT-IDENTICAL; a rank-scoped
# device-oom walks the cross-rank agreed ladder; a rank-1-scoped fault
# stays inert on rank 0; the report's comm section carries nonzero
# per-phase collective bytes), a fleet observatory smoke (12-request
# process-isolated chaos batch with --metrics-file: the Prometheus
# scrape parses, requests_total matches the verdict counts, rps > 0,
# and the v12 report carries request traces with worker-side compute
# spans), and the ROADMAP.md tier-1 pytest command.
# Exits nonzero on the first failing stage.
#
# Usage:  scripts/check_all.sh [--fast]
#         --fast skips the tier-1 pytest stage (lint + schema + chaos
#         smoke + diff self-test; lint + schema are the pair the
#         pre-commit hooks run).
set -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== [1/14] tpulint (zero findings, EMPTY baseline, standalone R9) =="
# full rule set, machine-readable: the gate is zero NEW findings AND an
# empty baseline — the ratchet finished shrinking in PR 17 and
# --write-baseline refuses to grow it back
python -m kaminpar_tpu.lint kaminpar_tpu/ --format json \
    > /tmp/_kmp_lint.json || { cat /tmp/_kmp_lint.json; exit 1; }
python - <<'EOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_lint.json"))
assert r["new"] == [], r["new"]
assert r["baseline_entries"] == 0, (
    f"baseline regrew to {r['baseline_entries']} entries — it must stay empty")
print(f"tpulint OK: 0 new finding(s), empty baseline")
EOF
# the cross-file schema-pin quad, standalone (R9 needs no file list)
python -m kaminpar_tpu.lint --select R9 --no-baseline || exit 1

echo "== [2/14] run-report schema (producer selftest, v1-v13 fixtures + v14 producer) =="
python scripts/check_report_schema.py --selftest || exit 1

echo "== [3/14] chaos smoke (KAMINPAR_TPU_FAULTS=all:nth=1) =="
rm -f /tmp/_kmp_chaos_report.json
KAMINPAR_TPU_FAULTS=all:nth=1 python -m kaminpar_tpu \
    "gen:rgg2d;n=4096;avg_degree=8;seed=1" -k 4 \
    --report-json /tmp/_kmp_chaos_report.json || exit 1
python scripts/check_report_schema.py /tmp/_kmp_chaos_report.json || exit 1
python - <<'EOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_chaos_report.json"))
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], f"chaos run failed the gate: {gate}"
assert r["faults"]["plan"] == "all:nth=1", r["faults"]
assert r["progress"], "v2 report carries no progress series"
# a fresh process always backend-compiles, so a zero count here means
# the accounting silently stopped recording, not a warm cache
assert r["compile"]["totals"]["compiles"] > 0, r["compile"]["totals"]
# v5 perf observatory: a fresh process backend-compiles, so at least
# one scope must carry cost (flops or bytes); barriers were crossed,
# so memory samples exist; shape buckets were padded, so pad rows exist
perf = r["perf"]
assert perf["enabled"], perf
assert any(
    e.get("bytes", 0) > 0 or e.get("flops", 0) > 0
    for e in perf["roofline"].values()
), "no roofline scope carries cost"
assert perf["memory"]["samples"], "no barrier memory samples"
assert perf["pad_waste"], "no pad-waste rows"
# v13 execution ledger: a fresh single-process run dispatches every
# compiled executable with the interception armed, so the launch total
# is nonzero, the CSR upload metered h2d bytes, and every roofline row
# that reports hbm_util is launch-honest (launches >= 1, honest=true —
# the PR-19 acceptance contract; a dishonest row here means the
# launch/cost join silently died)
led = r["ledger"]
assert led["enabled"], led
assert led["totals"]["launches"] > 0, led["totals"]
assert led["totals"]["uncosted_launches"] == 0, led["totals"]
assert led["transfers"]["totals"]["h2d_bytes"] > 0, \
    led["transfers"]["totals"]
util_rows = [(p, e) for p, e in perf["roofline"].items()
             if e.get("hbm_util") is not None]
assert util_rows, "no roofline row reports hbm_util"
dishonest = [p for p, e in util_rows
             if not (e.get("honest") and e.get("launches", 0) >= 1)]
assert not dishonest, f"launch-dishonest hbm_util rows: {dishonest}"
print(f"chaos smoke OK: {len(r['degraded'])} degraded event(s), "
      f"gate valid, cut={gate['cut_recomputed']}, "
      f"{len(r['progress'])} progress series, "
      f"{len(perf['roofline'])} roofline scope(s), "
      f"{len(perf['pad_waste'])} pad-waste row(s), "
      f"{led['totals']['launches']} launches (all costed), "
      f"h2d={led['transfers']['totals']['h2d_bytes']}B")
EOF
# the triage CLI must render the same report and exit 0 (non-empty
# roofline rows asserted by the flag)
python -m kaminpar_tpu.telemetry.top /tmp/_kmp_chaos_report.json \
    --require-roofline > /dev/null || exit 1
# v7 quality observatory: the chaos run coarsened >= 1 level, so the
# report must carry at least one cut-loss attribution row and the
# quality triage CLI must render it (exit 0; the flag asserts the row)
python -m kaminpar_tpu.telemetry.quality /tmp/_kmp_chaos_report.json \
    --require-attribution > /dev/null || exit 1
python - <<'EOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_chaos_report.json"))
q = r["quality"]
assert q["enabled"] and q["levels"], q.get("enabled")
rows = [lv for lv in q["levels"]
        if lv.get("gap") is not None and lv["level"] > 0]
assert rows, "no attribution rows in the chaos report"
# the exact per-level identity the observatory is built on
for lv in rows:
    assert lv["coarsening_locked"] + lv["refinement_left"] == lv["gap"], lv
# BENCH-line contract: bench.py must ALWAYS emit the two quality keys
# (null when a run carries no attribution — absence is the regression
# class bench_trend gates from r06 on)
import bench
line_keys = bench.quality_keys({})
assert set(line_keys) == {"coarsening_locked_frac",
                          "refinement_left_frac"}, line_keys
assert all(v is None for v in line_keys.values()), line_keys
filled = bench.quality_keys(r)
assert set(filled) == set(line_keys), filled
print(f"quality smoke OK: {len(rows)} attribution row(s), "
      f"locked_frac={q['totals'].get('coarsening_locked_frac')}, "
      "BENCH quality keys present")
EOF

echo "== [4/14] telemetry.diff self-test + BENCH trend/kernel gate =="
# identical reports must pass (rc 0)...
python -m kaminpar_tpu.telemetry.diff \
    /tmp/_kmp_chaos_report.json /tmp/_kmp_chaos_report.json || exit 1
# ...and an injected 50% wall + cut regression must FAIL (rc 1)
python - <<'EOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_chaos_report.json"))
r["result"]["cut"] = int(r["result"]["cut"] * 1.5) + 10
run = r.setdefault("run", {})
run["partition_seconds"] = float(run.get("partition_seconds", 1.0)) * 1.5 + 1.0
json.dump(r, open("/tmp/_kmp_chaos_report_perturbed.json", "w"))
EOF
if python -m kaminpar_tpu.telemetry.diff \
    /tmp/_kmp_chaos_report.json /tmp/_kmp_chaos_report_perturbed.json; then
    echo "ERROR: telemetry.diff accepted an injected 50% regression" >&2
    exit 1
fi
# the trend check is also the kernel regression gate: latest-round cut
# floor, 10M-coverage key presence (the r05 silent-drop class), and —
# on accelerator rounds — lp_coarsening_seconds / hbm_util floors
python scripts/bench_trend.py --check || exit 1


echo "== [5/14] preempt-and-resume smoke (SIGTERM mid-run + --resume) =="
CKPT=/tmp/_kmp_ckpt_smoke
rm -rf "$CKPT" /tmp/_kmp_preempt1.json /tmp/_kmp_preempt2.json
python -m kaminpar_tpu "gen:rgg2d;n=65536;avg_degree=8;seed=1" -k 8 \
    --checkpoint-dir "$CKPT" --report-json /tmp/_kmp_preempt1.json -q &
preempt_pid=$!
# signal as soon as the first barrier checkpoint lands => mid-pipeline
for _ in $(seq 1 240); do
    [ -f "$CKPT/manifest.json" ] && break
    sleep 0.5
done
kill -TERM "$preempt_pid" 2>/dev/null
wait "$preempt_pid" || { echo "ERROR: SIGTERM'd run exited nonzero" >&2; exit 1; }
python scripts/check_report_schema.py /tmp/_kmp_preempt1.json || exit 1
python - <<'EOF2' || exit 1
import json
r = json.load(open("/tmp/_kmp_preempt1.json"))
assert r["anytime"]["anytime"] is True, r["anytime"]
assert r["anytime"]["reason"] == "sigterm", r["anytime"]
ck = r["checkpoint"]
assert ck["enabled"] and ck["writes"] > 0 and not ck["memory_only"], ck
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], f"preempted run failed the gate: {gate}"
print(f"preempt OK: anytime at stage {r['anytime'].get('stage')}, "
      f"{ck['writes']} checkpoint write(s)")
EOF2
python -m kaminpar_tpu "gen:rgg2d;n=65536;avg_degree=8;seed=1" -k 8 \
    --checkpoint-dir "$CKPT" --resume --report-json /tmp/_kmp_preempt2.json -q \
    || exit 1
python scripts/check_report_schema.py /tmp/_kmp_preempt2.json || exit 1
python - <<'EOF2' || exit 1
import json
r = json.load(open("/tmp/_kmp_preempt2.json"))
assert r["checkpoint"]["resumed_from"] is not None, r["checkpoint"]
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], f"resumed run failed the gate: {gate}"
print(f"resume OK: resumed from {r['checkpoint']['resumed_from']}, "
      f"cut={gate['cut_recomputed']}")
EOF2

echo "== [6/14] serving smoke (mixed batch + faults + SIGTERM drain) =="
SERVE_DIR=/tmp/_kmp_serve_smoke
rm -rf "$SERVE_DIR"; mkdir -p "$SERVE_DIR"
python - <<'EOF3' || exit 1
# build the batch: 14 requests over 3 repeated shapes (result-cache
# hits), 1 deliberately malformed graph, 1 tight per-request deadline
import json

poison = "/tmp/_kmp_serve_smoke/poison.metis"
open(poison, "w").write("3 2\n1 2\n999999 1\n2\n")  # out-of-range id
A = {"graph": "gen:rgg2d;n=4096;avg_degree=8;seed=1", "k": 4, "seed": 1}
B = {"graph": "gen:rgg2d;n=4096;avg_degree=8;seed=2", "k": 4, "seed": 1}
C = {"graph": "gen:rgg2d;n=2048;avg_degree=8;seed=3", "k": 4, "seed": 1}
reqs = [dict(A, id=f"a{i}") for i in range(6)]
reqs += [dict(B, id=f"b{i}") for i in range(4)]
reqs += [dict(C, id=f"c{i}") for i in range(4)]
reqs.append({"graph": poison, "k": 4, "id": "poison"})
reqs.append({"graph": "gen:rgg2d;n=65536;avg_degree=8;seed=9", "k": 8,
             "seed": 1, "deadline_s": 0.05, "id": "tight-deadline"})
assert len(reqs) == 16
json.dump({"requests": reqs}, open("/tmp/_kmp_serve_smoke/batch.json", "w"))
EOF3
KAMINPAR_TPU_FAULTS=refiner:nth=1 python -m kaminpar_tpu \
    --serve-batch "$SERVE_DIR/batch.json" \
    --report-json "$SERVE_DIR/report.json" \
    || { echo "ERROR: serving batch exited nonzero (isolation broken)" >&2; exit 1; }
python scripts/check_report_schema.py "$SERVE_DIR/report.json" || exit 1
python - <<'EOF3' || exit 1
import json
r = json.load(open("/tmp/_kmp_serve_smoke/report.json"))
s = r["serving"]
assert s["enabled"] and len(s["requests"]) == 16, len(s["requests"])
c = s["counts"]
assert sum(c.values()) == 16, c
assert c["failed"] == 1, c  # the poisoned request, alone
assert c["anytime"] >= 1, c  # the tight per-request deadline fired
assert c["served"] >= 12, c
by_id = {q["request_id"]: q for q in s["requests"]}
assert by_id["poison"]["verdict"] == "failed", by_id["poison"]
assert by_id["tight-deadline"]["verdict"] == "anytime", by_id["tight-deadline"]
# every completed request is gate-valid and feasible
for q in s["requests"]:
    if q["verdict"] in ("served", "anytime", "degraded"):
        assert q["feasible"], q
        assert q.get("gate_valid", True), q
# bounded result cache: hit-rate over the repeated-shape subset
assert s["cache"]["hit_rate"] >= 0.5, s["cache"]
assert s["cache"]["result"]["entries"] <= s["cache"]["result"]["max_entries"]
# the injected refiner fault degraded ONE request, not the process
assert r["faults"]["injected"], r["faults"]
# serving latency histograms: every executed request recorded a total,
# and the percentile surface is populated (p50 <= p95 <= p99)
lat = s["latency"]["phases"]["total"]
assert lat["count"] >= 15, lat  # 16 requests minus the rejected-only ones
assert lat["p50_ms"] is not None and lat["p95_ms"] is not None, lat
assert lat["p50_ms"] <= lat["p95_ms"] <= lat["p99_ms"], lat
assert s["latency"]["classes"], s["latency"]
print(f"serving smoke OK: counts={c}, "
      f"cache_hit_rate={s['cache']['hit_rate']}, "
      f"exec_buckets={s['cache']['executable']['buckets']}, "
      f"p95_ms={lat['p95_ms']}")
EOF3
python - <<'EOF3' || exit 1
# drain batch: 12 slow distinct requests, SIGTERM lands mid-batch
import json

reqs = [{"graph": f"gen:rgg2d;n=65536;avg_degree=8;seed={i}", "k": 8,
         "seed": 1, "id": f"d{i}"} for i in range(12)]
json.dump({"requests": reqs}, open("/tmp/_kmp_serve_smoke/drain.json", "w"))
EOF3
python -m kaminpar_tpu --serve-batch "$SERVE_DIR/drain.json" \
    --report-json "$SERVE_DIR/drain_report.json" -q &
serve_pid=$!
# land the signal mid-batch: past interpreter/handler startup (~2 s),
# well inside the first request's compile+run (~10 s) of 12 requests
sleep 5
kill -TERM "$serve_pid" 2>/dev/null
wait "$serve_pid" \
    || { echo "ERROR: SIGTERM'd serving batch exited nonzero" >&2; exit 1; }
python scripts/check_report_schema.py "$SERVE_DIR/drain_report.json" || exit 1
python - <<'EOF3' || exit 1
import json
r = json.load(open("/tmp/_kmp_serve_smoke/drain_report.json"))
s = r["serving"]
# SIGTERM drained the queue: EVERY request still got a verdict in a
# schema-valid report — in-flight wound down (anytime), queued rejected
assert s["drained"] is True, s
assert len(s["requests"]) == 12, len(s["requests"])
c = s["counts"]
assert sum(c.values()) == 12, c
drained = [q for q in s["requests"]
           if q["verdict"] == "rejected" and q.get("reason") == "draining"]
assert drained, c
print(f"drain OK: counts={c} ({len(drained)} drained)")
EOF3


echo "== [7/14] supervision smoke (worker hang/crash containment) =="
SUP_DIR=/tmp/_kmp_sup_smoke
rm -rf "$SUP_DIR"; mkdir -p "$SUP_DIR"
SUP_START_NS=$(python -c "import time; print(time.time_ns())")
python - <<'EOF7' || exit 1
# 10 requests, distinct seeds (no cache hits — the chaos nth counters
# count pool executions): #3 is the hang target (2 s hard ceiling, the
# injected chaos makes the worker sleep forever), #6 the crash target
# (worker SIGKILLs itself); worker_max_requests=4 forces >= 1 recycle
# across the 8 clean requests
import json

reqs = []
for i in range(1, 11):
    r = {"graph": f"gen:rgg2d;n=4096;avg_degree=8;seed={i}", "k": 4,
         "seed": 1, "id": f"s{i}"}
    if i == 3:
        r["hard_deadline_s"] = 2.0
    reqs.append(r)
json.dump({"config": {"worker_max_requests": 4}, "requests": reqs},
          open("/tmp/_kmp_sup_smoke/batch.json", "w"))
EOF7
KAMINPAR_TPU_FAULTS=worker-hang:nth=3,worker-crash:nth=6 \
    python -m kaminpar_tpu --serve-batch "$SUP_DIR/batch.json" \
    --serve-isolation process --heartbeat-file "$SUP_DIR/heartbeat" \
    --report-json "$SUP_DIR/report.json" \
    || { echo "ERROR: supervised batch exited nonzero (containment broken)" >&2; exit 1; }
python scripts/check_report_schema.py "$SUP_DIR/report.json" || exit 1
SUP_START_NS=$SUP_START_NS python - <<'EOF7' || exit 1
import json, os

r = json.load(open("/tmp/_kmp_sup_smoke/report.json"))
assert r["schema_version"] == 14, r["schema_version"]
s = r["serving"]
by_id = {q["request_id"]: q for q in s["requests"]}
assert len(by_id) == 10, len(by_id)
# the two injected failures — and ONLY those two — failed, with the
# supervision reasons and the per-request hard-ceiling field recorded
assert by_id["s3"]["verdict"] == "failed", by_id["s3"]
assert by_id["s3"]["reason"] == "worker-hang", by_id["s3"]
assert by_id["s3"]["hard_ceiling_s"] == 2.0, by_id["s3"]
assert by_id["s6"]["verdict"] == "failed", by_id["s6"]
assert by_id["s6"]["reason"] == "worker-crash", by_id["s6"]
served = [q for q in s["requests"] if q["verdict"] == "served"]
assert len(served) >= 8, s["counts"]
for q in served:
    assert q["feasible"] and q.get("gate_valid", True), q
assert s["counts"]["failed"] == 2, s["counts"]
assert s["counts"].get("worker-hang") == 1, s["counts"]
assert s["counts"].get("worker-crash") == 1, s["counts"]
# supervision section: workers were spawned, the hung one was killed,
# the crashed one detected, and the clean tail reused a recycled warm
# worker; the hang event carries its stage + ceiling
sup = r["supervision"]
assert sup["enabled"] and sup["isolation"] == "process", sup
w = sup["workers"]
assert w["spawned"] >= 2 and w["killed"] >= 1 and w["crashed"] >= 1, w
assert w["recycled"] >= 1, w
assert sup["hangs"] and sup["hangs"][0]["ceiling_s"] == 2.0, sup["hangs"]
# heartbeat: touched at barriers + watchdog ticks + per-request, and
# the file's mtime advanced past the stage's start stamp
hb = sup["heartbeat"]
assert hb["file"].endswith("heartbeat") and hb["count"] >= 10, hb
mtime_ns = os.stat("/tmp/_kmp_sup_smoke/heartbeat").st_mtime_ns
assert mtime_ns > int(os.environ["SUP_START_NS"]), (
    mtime_ns, os.environ["SUP_START_NS"])
print(f"supervision smoke OK: counts={s['counts']}, workers={w}, "
      f"{len(sup['hangs'])} hang(s), heartbeat={hb['count']} touch(es)")
EOF7

echo "== [8/14] memory-governor smoke (tiny budget + forced spill + serving) =="
MEM_DIR=/tmp/_kmp_mem_smoke
rm -rf "$MEM_DIR"; mkdir -p "$MEM_DIR"
# an artificially small budget: 25% of the rung-0 estimate for the shape
BUDGET=$(python - <<'PYEOF'
from kaminpar_tpu.resilience.memory import estimate_run_bytes
print(int(estimate_run_bytes(65536, 65536 * 8, 8) * 0.25))
PYEOF
) || exit 1
KAMINPAR_TPU_HBM_BYTES=$BUDGET python -m kaminpar_tpu \
    "gen:rgg2d;n=65536;avg_degree=8;seed=1" -k 8 \
    --report-json "$MEM_DIR/budget.json" -q || exit 1
python scripts/check_report_schema.py "$MEM_DIR/budget.json" || exit 1
python - <<'PYEOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_mem_smoke/budget.json"))
mb = r["memory_budget"]
# the never-RESOURCE_EXHAUSTED contract: exit 0 (above), gate-valid,
# ladder engaged (rung >= 1), nothing exhausted
assert mb["enabled"] and mb["rung"] >= 1 and not mb["exhausted"], mb
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], gate
print(f"tiny-budget OK: rung={mb['rung']} ({mb.get('rung_name')}), "
      f"budget={mb.get('budget_bytes')} estimate={mb.get('estimate_bytes')}")
PYEOF
# forced rung 2: host-spilled hierarchy — spill AND reload events must
# be present and the run still gate-valid
KAMINPAR_TPU_MEM_RUNG=2 KAMINPAR_TPU_HBM_BYTES=$((BUDGET * 100)) \
    python -m kaminpar_tpu "gen:rgg2d;n=65536;avg_degree=8;seed=1" -k 8 \
    --contraction-limit 500 --report-json "$MEM_DIR/spill.json" -q || exit 1
python - <<'PYEOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_mem_smoke/spill.json"))
mb = r["memory_budget"]
spills = [e for e in r["events"] if e["name"] == "memory-spill"]
reloads = [e for e in r["events"] if e["name"] == "memory-reload"]
assert mb["rung"] == 2 and spills and reloads, (mb, len(spills))
assert mb["spills"]["count"] >= 1 and mb["spills"]["reloads"] >= 1, mb
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], gate
print(f"spill smoke OK: {len(spills)} spill(s), {len(reloads)} reload(s), "
      f"{mb['spills']['bytes']} bytes spilled")
PYEOF
# serving batch: one oversized request must be rejected with the
# structured insufficient-memory verdict (sized from the gen spec,
# never loaded); the fitting request is served normally
python - <<'PYEOF' || exit 1
import json
reqs = [
    {"graph": "gen:rgg2d;n=4096;avg_degree=8;seed=1", "k": 4,
     "seed": 1, "id": "fits"},
    {"graph": "gen:rgg2d;n=4194304;avg_degree=16;seed=2", "k": 64,
     "id": "oversized"},
]
json.dump({"requests": reqs},
          open("/tmp/_kmp_mem_smoke/batch.json", "w"))
PYEOF
KAMINPAR_TPU_HBM_BYTES=268435456 python -m kaminpar_tpu \
    --serve-batch "$MEM_DIR/batch.json" \
    --report-json "$MEM_DIR/serve.json" -q || exit 1
python scripts/check_report_schema.py "$MEM_DIR/serve.json" || exit 1
python - <<'PYEOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_mem_smoke/serve.json"))
by_id = {q["request_id"]: q for q in r["serving"]["requests"]}
assert by_id["fits"]["verdict"] == "served", by_id["fits"]
assert by_id["oversized"]["verdict"] == "rejected", by_id["oversized"]
assert by_id["oversized"]["reason"] == "insufficient-memory", by_id
print("serving insufficient-memory OK")
PYEOF

echo "== [9/14] out-of-core streaming smoke (--scheme external) =="
EXT_DIR=/tmp/_kmp_ext_smoke
rm -rf "$EXT_DIR"; mkdir -p "$EXT_DIR"
# a budget at 25% of the in-core estimate: the external scheme must
# stream the fine level (never uploading it), stay gate-valid, and
# report the schema-v9 external section with overlap > 0
EXT_BUDGET=$(python - <<'PYEOF'
from kaminpar_tpu.resilience.memory import estimate_run_bytes
print(int(estimate_run_bytes(65536, 65536 * 8, 8) * 0.25))
PYEOF
) || exit 1
EXT_GRAPH="gen:rgg2d;n=65536;avg_degree=8;seed=1"
KAMINPAR_TPU_HBM_BYTES=$EXT_BUDGET python -m kaminpar_tpu "$EXT_GRAPH" \
    -k 8 --scheme external --report-json "$EXT_DIR/ref.json" -q || exit 1
python scripts/check_report_schema.py "$EXT_DIR/ref.json" || exit 1
python - <<'PYEOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_ext_smoke/ref.json"))
assert r["schema_version"] == 14, r["schema_version"]
ext = r["external"]
# the out-of-core contract: >= 1 streamed level, the fine level NEVER
# device-resident, and the chunk pipeline actually overlapped
assert ext["enabled"] and ext["streamed_levels"] >= 1, ext
assert ext["fine_device_resident_bytes"] == 0, ext
assert ext["chunks_total"] >= 1 and ext["decoded_bytes"] > 0, ext
assert ext["overlap_frac"] > 0, ext
streams = [e for e in r["events"] if e["name"] == "stream"]
assert streams, "no stream telemetry events"
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], gate
print(f"external smoke OK: {ext['streamed_levels']} level(s), "
      f"{ext['chunks_total']} chunk(s), overlap={ext['overlap_frac']}, "
      f"cut={gate['cut_recomputed']}")
PYEOF
# kill-and-resume MID-STREAM (hard preemption at the first streamed
# level's barrier): the resume must be CUT-IDENTICAL to the reference
if KAMINPAR_TPU_STOP_AT='stream-coarsen:0!' \
    KAMINPAR_TPU_HBM_BYTES=$EXT_BUDGET python -m kaminpar_tpu \
    "$EXT_GRAPH" -k 8 --scheme external \
    --checkpoint-dir "$EXT_DIR/ckpt" -q 2> /dev/null; then
    echo "ERROR: simulated mid-stream kill did not kill the run" >&2
    exit 1
fi
[ -f "$EXT_DIR/ckpt/manifest.json" ] \
    || { echo "ERROR: killed external run left no manifest" >&2; exit 1; }
KAMINPAR_TPU_HBM_BYTES=$EXT_BUDGET python -m kaminpar_tpu "$EXT_GRAPH" \
    -k 8 --scheme external --checkpoint-dir "$EXT_DIR/ckpt" --resume \
    --report-json "$EXT_DIR/res.json" -q || exit 1
python - <<'PYEOF' || exit 1
import json
ref = json.load(open("/tmp/_kmp_ext_smoke/ref.json"))
res = json.load(open("/tmp/_kmp_ext_smoke/res.json"))
assert res["checkpoint"].get("resumed_from"), res["checkpoint"]
assert res["output_gate"]["valid"], res["output_gate"]
assert res["result"]["cut"] == ref["result"]["cut"], (
    "mid-stream resume is not cut-identical: "
    f"ref {ref['result']['cut']} vs resumed {res['result']['cut']}")
print(f"external resume OK: resumed from "
      f"{res['checkpoint']['resumed_from']}, cut={res['result']['cut']} "
      "(identical to the reference)")
PYEOF

echo "== [10/14] dynamic repartition smoke (8-delta chain + chaos + bucket crossing) =="
DYN_DIR=/tmp/_kmp_dynamic_smoke
rm -rf "$DYN_DIR"; mkdir -p "$DYN_DIR"
# synthesize the chain OUTSIDE the fault plan (the generator applies
# deltas to a scratch session and must not consume the injection
# budget): 7 small ~1% churn batches + ONE bucket-crossing batch that
# inserts past the padded edge bucket's slack
python - <<'PYEOF' || exit 1
import json
import numpy as np
from kaminpar_tpu import caching
from kaminpar_tpu.dynamic import GraphSession, random_delta_batch, synth_chain
from kaminpar_tpu.graphs.factories import generate

g = generate("gen:rgg2d;n=4096;avg_degree=8;seed=1")
scratch = GraphSession("gen", g, k=4)
batches = []
for i in range(8):
    if i == 4:
        # bucket-crossing delta: insert past the m bucket's slack
        m_pad = caching.pad_size(max(scratch.graph.m, 1))
        slack_und = (m_pad - scratch.graph.m) // 2
        b = random_delta_batch(scratch.graph, seed=900,
                               edge_churn=float(slack_und + 64)
                               / max(scratch.graph.m // 2, 1),
                               insert_frac=1.0)
    else:
        b = random_delta_batch(scratch.graph, seed=300 + i,
                               edge_churn=0.01)
    info = scratch.apply(b)
    batches.append(b.to_dict())
assert scratch.rebuilds >= 1, "no bucket-crossing delta synthesized"
json.dump({"deltas": batches}, open("/tmp/_kmp_dynamic_smoke/deltas.json", "w"))
print(f"chain synthesized: {len(batches)} deltas, "
      f"{scratch.in_place} in-place / {scratch.rebuilds} rebuild")
PYEOF
# drive the chain with one injected dynamic-apply chaos fault (forces
# one in-place-eligible delta down the rebuild path)
KAMINPAR_TPU_FAULTS=dynamic-apply:nth=2 python -m kaminpar_tpu \
    "gen:rgg2d;n=4096;avg_degree=8;seed=1" -k 4 -s 1 \
    --delta-batch "$DYN_DIR/deltas.json" \
    --report-json "$DYN_DIR/report.json" -q || exit 1
python scripts/check_report_schema.py "$DYN_DIR/report.json" || exit 1
python - <<'PYEOF' || exit 1
import json
r = json.load(open("/tmp/_kmp_dynamic_smoke/report.json"))
d = r["dynamic"]
assert d["enabled"], d
sess = d["sessions"][0]
assert sess["deltas_applied"] == 8, sess
# >= 1 in-place and >= 1 rebuild (the bucket-crossing delta plus the
# injected dynamic-apply fault both force rebuilds)
assert sess["in_place"] >= 1 and sess["rebuilds"] >= 1, sess
inj = [row for row in r["faults"]["injected"]
       if row["site"] == "dynamic-apply"]
assert inj, r["faults"]
# every repartition gate-valid...
reparts = [row for row in d["decisions"] if row.get("step", 0) >= 1]
assert len(reparts) == 8, [row.get("step") for row in d["decisions"]]
bad_gate = [row for row in d["decisions"]
            if row.get("gate_valid") is False]
assert not bad_gate, bad_gate
# ...and the cut trajectory stays inside the diff-gate threshold: every
# step either passed the PR-4 cut gate vs its pre-delta baseline or was
# escalated to the cold run and kept the better of the two
unstable = [row for row in reparts
            if row.get("stable") is False and not row.get("escalated")]
assert not unstable, unstable
traj = d["cut_trajectory"]
assert len(traj) == 9 and all(isinstance(c, int) for c in traj), traj
counts = d["counts"]
print(f"dynamic smoke OK: warm={counts['warm']} cold={counts['cold']} "
      f"in_place={counts['in_place']} rebuilds={counts['rebuilds']} "
      f"trajectory={traj}")
PYEOF

echo "== [11/14] dist resilience smoke (preempt+resume, rank-scoped chaos) =="
DIST_DIR=/tmp/_kmp_dist_smoke
rm -rf "$DIST_DIR"; mkdir -p "$DIST_DIR"
DIST_XLA="--xla_force_host_platform_device_count=8"
DGRAPH="gen:rgg2d;n=65536;avg_degree=8;seed=1"
# reference (uninterrupted) run: the cut-identity anchor
XLA_FLAGS="$DIST_XLA" python -m kaminpar_tpu.dcli "$DGRAPH" -k 4 -n 4 \
    --report-json "$DIST_DIR/ref.json" -q || exit 1
python - <<'EOF8' || exit 1
# v12 comm promotion: a fresh dist process traces every phase, so the
# per-phase rollup must be populated with nonzero bytes and internally
# consistent (headline == sum of phases == sum of records)
import json
r = json.load(open("/tmp/_kmp_dist_smoke/ref.json"))
comm = r["comm"]
phases = comm["phases"]
assert phases, "dist run rolled up no comm phases"
assert comm["bytes_total"] > 0, comm["bytes_total"]
assert any(p["bytes_total"] > 0 for p in phases.values()), phases
assert comm["bytes_total"] == sum(
    p["bytes_total"] for p in phases.values()), comm["bytes_total"]
rec_total = sum(
    rec["payload_bytes_per_device"] for rec in comm["records"])
assert comm["bytes_total"] == rec_total, (comm["bytes_total"], rec_total)
print(f"dist comm OK: {len(phases)} phase(s), "
      f"bytes_total={comm['bytes_total']}")
EOF8
# preempt: SIGTERM as soon as the first dist barrier checkpoint lands
XLA_FLAGS="$DIST_XLA" python -m kaminpar_tpu.dcli "$DGRAPH" -k 4 -n 4 \
    --checkpoint-dir "$DIST_DIR/ckpt" \
    --report-json "$DIST_DIR/pre.json" -q &
dist_pid=$!
for _ in $(seq 1 240); do
    [ -f "$DIST_DIR/ckpt/manifest.json" ] && break
    sleep 0.5
done
kill -TERM "$dist_pid" 2>/dev/null
wait "$dist_pid" \
    || { echo "ERROR: SIGTERM'd dist run exited nonzero" >&2; exit 1; }
python scripts/check_report_schema.py "$DIST_DIR/pre.json" || exit 1
python - <<'EOF8' || exit 1
import json
r = json.load(open("/tmp/_kmp_dist_smoke/pre.json"))
assert r["anytime"]["anytime"] is True, r["anytime"]
assert r["anytime"]["reason"] == "sigterm", r["anytime"]
ck = r["checkpoint"]
assert ck["enabled"] and ck["writes"] > 0, ck
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], gate
dr = r["dist_resilience"]
assert dr["enabled"] and dr["audits"] >= 1, dr
assert len(dr["shard_fingerprints"]) == 4, dr
print(f"dist preempt OK: anytime at {r['anytime'].get('stage')}, "
      f"{ck['writes']} checkpoint write(s), {dr['audits']} audit(s)")
EOF8
# resume after the graceful wind-down: the preempted run ran its
# mandatory tail and checkpointed its (anytime) result — resume must
# return EXACTLY that partition (cut-identical to the preempted run's
# own result, resumed_from the final `result` snapshot)
XLA_FLAGS="$DIST_XLA" python -m kaminpar_tpu.dcli "$DGRAPH" -k 4 -n 4 \
    --checkpoint-dir "$DIST_DIR/ckpt" --resume \
    --report-json "$DIST_DIR/res.json" -q || exit 1
python scripts/check_report_schema.py "$DIST_DIR/res.json" || exit 1
python - <<'EOF8' || exit 1
import json
pre = json.load(open("/tmp/_kmp_dist_smoke/pre.json"))
res = json.load(open("/tmp/_kmp_dist_smoke/res.json"))
assert res["checkpoint"].get("resumed_from"), res["checkpoint"]
assert res["output_gate"]["valid"], res["output_gate"]
assert res["result"]["cut"] == pre["result"]["cut"], (
    "resume did not restore the preempted run's result: "
    f"preempted {pre['result']['cut']} vs resumed {res['result']['cut']}")
print(f"dist resume OK: resumed from {res['checkpoint']['resumed_from']}, "
      f"cut={res['result']['cut']} (identical to the preempted result)")
EOF8
# hard kill MID-PIPELINE (the SimulatedPreemption test hook — no
# mandatory tail, like a real SIGKILL): the resume re-enters at the
# recorded dist barrier and must be CUT-IDENTICAL to the uninterrupted
# reference (full-hierarchy dist resume)
rm -rf "$DIST_DIR/ckpt"
if KAMINPAR_TPU_STOP_AT='dist-uncoarsen:1!' XLA_FLAGS="$DIST_XLA" \
    python -m kaminpar_tpu.dcli "$DGRAPH" -k 4 -n 4 \
    --checkpoint-dir "$DIST_DIR/ckpt" -q 2> /dev/null; then
    echo "ERROR: simulated hard kill did not kill the run" >&2; exit 1
fi
[ -f "$DIST_DIR/ckpt/manifest.json" ] \
    || { echo "ERROR: hard-killed run left no manifest" >&2; exit 1; }
XLA_FLAGS="$DIST_XLA" python -m kaminpar_tpu.dcli "$DGRAPH" -k 4 -n 4 \
    --checkpoint-dir "$DIST_DIR/ckpt" --resume \
    --report-json "$DIST_DIR/hard.json" -q || exit 1
python - <<'EOF8' || exit 1
import json
ref = json.load(open("/tmp/_kmp_dist_smoke/ref.json"))
hard = json.load(open("/tmp/_kmp_dist_smoke/hard.json"))
assert hard["checkpoint"].get("resumed_from") == "dist-uncoarsen:1", (
    hard["checkpoint"])
assert hard["output_gate"]["valid"], hard["output_gate"]
assert hard["result"]["cut"] == ref["result"]["cut"], (
    "mid-pipeline dist resume is not cut-identical: "
    f"ref {ref['result']['cut']} vs resumed {hard['result']['cut']}")
print(f"dist hard-kill resume OK: re-entered at dist-uncoarsen:1, "
      f"cut={hard['result']['cut']} (identical to the reference)")
EOF8
# rank-scoped chaos: a single-rank DeviceOOM walks the run down the
# cross-rank agreed ladder (rung >= 1) and still ends gate-valid...
KAMINPAR_TPU_FAULTS=device-oom@rank=0:nth=1 XLA_FLAGS="$DIST_XLA" \
    python -m kaminpar_tpu.dcli "$DGRAPH" -k 4 -n 4 \
    --report-json "$DIST_DIR/chaos0.json" || exit 1
python - <<'EOF8' || exit 1
import json
r = json.load(open("/tmp/_kmp_dist_smoke/chaos0.json"))
deg = [d["attrs"] for d in r["degraded"]
       if d["attrs"]["site"] == "device-oom"]
assert deg, r["degraded"]
last = deg[-1]
assert last["rung"] >= 1 and last["injected"], last
assert last.get("triggering_rank") == 0, last
mb = r["memory_budget"]
assert mb["enabled"] and mb["rung"] >= 1 and not mb["exhausted"], mb
assert r["output_gate"]["valid"], r["output_gate"]
assert r["dist_resilience"]["ladder"]["rung"] >= 1, r["dist_resilience"]
print(f"rank-scoped chaos OK: rung={mb['rung']} "
      f"triggered by rank {last.get('triggering_rank')}")
EOF8
# ...and the SAME fault scoped to rank 1 is inert on this rank-0 fleet
KAMINPAR_TPU_FAULTS=device-oom@rank=1:nth=1 XLA_FLAGS="$DIST_XLA" \
    python -m kaminpar_tpu.dcli "$DGRAPH" -k 4 -n 4 \
    --report-json "$DIST_DIR/chaos1.json" -q || exit 1
python - <<'EOF8' || exit 1
import json
r = json.load(open("/tmp/_kmp_dist_smoke/chaos1.json"))
assert r["degraded"] == [], r["degraded"]
assert r["memory_budget"] == {"enabled": False} or \
    r["memory_budget"].get("rung", 0) == 0, r["memory_budget"]
print("rank-scope inert OK: rank=1 plan fired nothing on rank 0")
EOF8

echo "== [12/14] fleet observatory smoke (live metrics + request traces) =="
OBS_DIR=/tmp/_kmp_obs_smoke
rm -rf "$OBS_DIR"; mkdir -p "$OBS_DIR"
python - <<'EOF9' || exit 1
# 12 requests with distinct seeds (every one a real pool execution);
# chaos: #5 crashes its worker — the batch keeps serving and the live
# counters must account the failure next to the successes
import json
reqs = [{"graph": f"gen:rgg2d;n=4096;avg_degree=8;seed={i}", "k": 4,
         "seed": 1, "id": f"o{i}"} for i in range(1, 13)]
json.dump({"requests": reqs}, open("/tmp/_kmp_obs_smoke/batch.json", "w"))
EOF9
KAMINPAR_TPU_FAULTS=worker-crash:nth=5 python -m kaminpar_tpu \
    --serve-batch "$OBS_DIR/batch.json" --serve-isolation process \
    --metrics-file "$OBS_DIR/metrics.prom" \
    --report-json "$OBS_DIR/report.json" \
    | tee "$OBS_DIR/stdout.log" \
    || { echo "ERROR: observed batch exited nonzero" >&2; exit 1; }
grep -E "^SERVING .* rps=" "$OBS_DIR/stdout.log" > /dev/null \
    || { echo "ERROR: SERVING line carries no rps= field" >&2; exit 1; }
python scripts/check_report_schema.py "$OBS_DIR/report.json" || exit 1
python - <<'EOF9' || exit 1
import json, re

# -- the scrape: well-formed Prometheus text exposition (0.0.4)
lines = open("/tmp/_kmp_obs_smoke/metrics.prom").read().splitlines()
assert lines, "empty metrics scrape"
sample_re = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9eE+.]+$')
samples = {}
for ln in lines:
    if not ln or ln.startswith("#"):
        continue
    assert sample_re.match(ln), f"unparseable sample line: {ln!r}"
    name_labels, value = ln.rsplit(" ", 1)
    samples[name_labels] = float(value)
r = json.load(open("/tmp/_kmp_obs_smoke/report.json"))
assert r["schema_version"] == 14, r["schema_version"]
counts = r["serving"]["counts"]
# the live counter and the post-mortem report agree on every verdict
# (counts also carries reason sub-keys like worker-crash — sum the
# five verdicts only)
VERDICTS = ("served", "anytime", "degraded", "rejected", "failed")
req_total = sum(v for k, v in samples.items()
                if k.startswith("kmp_requests_total{"))
assert req_total == sum(counts[v] for v in VERDICTS) == 12, (
    req_total, counts)
assert samples.get('kmp_requests_total{verdict="failed"}', 0) \
    == counts.get("failed", 0) == 1, (samples, counts)
assert samples.get("kmp_requests_per_second", 0) > 0, samples
assert samples.get('kmp_worker_pool{event="crashed"}', 0) >= 1, samples
assert samples.get('kmp_worker_pool{event="spawned"}', 0) >= 2, samples
# -- the traces: v12 tracing section populated, worker boundary
# visible (spawn/ship overhead span + the worker's own compute scopes)
tr = r["tracing"]
assert tr["enabled"] and tr["traces"], tr.get("enabled")
spans = [(s["name"], s["origin"])
         for t in tr["traces"] for s in t["spans"]]
assert ("worker-compute", "worker") in spans, sorted(set(spans))
assert any(n == "worker-spawn-ship" for n, _ in spans), sorted(set(spans))
# the service-side phase taxonomy is complete on >= 1 trace
need = {"admission", "queue-wait", "resolve", "compute", "gate"}
assert any(need <= {s["name"] for s in t["spans"]}
           for t in tr["traces"]), sorted(set(spans))
# throughput rides the serving summary too (the SERVING line's rps=)
thr = r["serving"]["throughput"]
assert thr["requests_per_second"] > 0 and thr["queue_peak"] >= 1, thr
print(f"fleet observatory OK: {len(samples)} sample(s), "
      f"rps={samples['kmp_requests_per_second']}, "
      f"{len(tr['traces'])} trace(s), counts={counts}")
EOF9

echo "== [13/14] integrity smoke (corruption chaos: detect, retry, recover) =="
# an uninjected reference run, then the SAME seed with a bit flipped
# inside the first contraction: the sentinel must name the invariant,
# one retry from the last clean barrier must recover, and the final
# cut must equal the reference (detection is lossless, not lossy)
rm -f /tmp/_kmp_integ_ref.json /tmp/_kmp_integ_chaos.json
python -m kaminpar_tpu "gen:rgg2d;n=4096;avg_degree=8;seed=1" -k 4 \
    --report-json /tmp/_kmp_integ_ref.json || exit 1
KAMINPAR_TPU_FAULTS=bit-flip:contraction:nth=1 \
python -m kaminpar_tpu "gen:rgg2d;n=4096;avg_degree=8;seed=1" -k 4 \
    --report-json /tmp/_kmp_integ_chaos.json || exit 1
python scripts/check_report_schema.py /tmp/_kmp_integ_chaos.json || exit 1
python - <<'EOF10' || exit 1
import json
ref = json.load(open("/tmp/_kmp_integ_ref.json"))
r = json.load(open("/tmp/_kmp_integ_chaos.json"))
assert r["schema_version"] >= 14, r["schema_version"]
integ = r["integrity"]
# detection at the right site, with the invariant named
assert integ["enabled"] and integ["violations"], integ
inv = {v["invariant"] for v in integ["violations"]}
assert "edge-weight-conservation" in inv or "coarse-csr-symmetry" in inv, inv
assert all(v["level"] is not None for v in integ["violations"]), integ
# one retry from the last clean barrier, recovered verdict
assert integ["retries"] == 1 and integ["recovered"] == 1, integ
assert integ["verdict"] == "recovered", integ
# recovery is lossless: gate-valid AND cut identical to the
# uninjected reference run (deterministic seeds)
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], gate
assert r["result"]["cut"] == ref["result"]["cut"], (
    r["result"]["cut"], ref["result"]["cut"])
# the reference run is clean end to end
ri = ref["integrity"]
assert ri["enabled"] and ri["verdict"] == "clean" and not ri["violations"], ri
print(f"integrity smoke OK: {sorted(inv)} detected at level "
      f"{integ['violations'][0]['level']}, 1 retry, recovered, "
      f"cut={r['result']['cut']} == reference")
EOF10
# spill-corrupt leg: a budget-forced external run re-reads spilled
# chunks; the flipped byte must be caught by the per-chunk digest and
# recovered locally (re-decode) — run still gate-valid, mismatch
# counted in the digest tally
rm -rf /tmp/_kmp_integ_spill.json /tmp/_kmp_integ_spill_dir
mkdir -p /tmp/_kmp_integ_spill_dir
KAMINPAR_TPU_FAULTS=spill-corrupt:nth=1 \
python -m kaminpar_tpu "gen:rgg2d;n=4096;avg_degree=8;seed=1" -k 4 \
    --scheme external --memory-budget 2500000 \
    --external-spill-dir /tmp/_kmp_integ_spill_dir \
    --report-json /tmp/_kmp_integ_spill.json || exit 1
python - <<'EOF11' || exit 1
import json
r = json.load(open("/tmp/_kmp_integ_spill.json"))
integ = r["integrity"]
gate = r["output_gate"]
assert gate["checked"] and gate["valid"], gate
dig = integ.get("digests") or {}
if dig.get("mismatched"):
    # the spill tier engaged and the corruption was caught + recovered
    sites = {v.get("site") for v in integ["violations"]}
    assert "spill-corrupt" in sites, sites
    print(f"integrity smoke OK: spill-corrupt caught "
          f"({dig['mismatched']} digest mismatch), recovered locally")
else:
    # plan armed but the run never re-read a spilled chunk (budget
    # heuristics can change): the fault must simply not have fired —
    # silence here would otherwise hide a dead detector
    assert not [e for e in r["faults"]["injected"]
                if e["site"] == "spill-corrupt"], r["faults"]["injected"]
    print("integrity smoke OK: spill tier not re-read this run "
          "(no injection consumed); detection covered by tier-1 tests")
EOF11

if [ "${1:-}" = "--fast" ]; then
    echo "== [14/14] tier-1 pytest: SKIPPED (--fast) =="
    exit 0
fi

echo "== [14/14] tier-1 pytest (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit $rc
