#!/bin/bash
# Retry the TPU probe until the tunnel grants a chip; log everything.
LOG=/tmp/tpu_watch.log
echo "=== watcher start $(date) ===" >> $LOG
for i in $(seq 1 100); do
  echo "--- attempt $i $(date) ---" >> $LOG
  ATT=$(mktemp)
  python /root/repo/scripts/probe_dynamic_gather.py > $ATT 2>&1
  rc=$?
  cat $ATT >> $LOG
  echo "--- attempt $i exit $rc $(date) ---" >> $LOG
  if [ $rc -eq 0 ] && grep -q ns_per_index $ATT; then rm -f $ATT;
    echo "=== SUCCESS $(date) ===" >> $LOG
    exit 0
  fi
  rm -f $ATT
  sleep 120
done
