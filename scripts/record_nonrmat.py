#!/usr/bin/env python
"""Record partition quality on NON-RMAT (real-topology) graphs.

VERDICT r4 weak #2: every headline cut so far was RMAT, where the
reference's default preset is known-weak.  This script generates
real-topology instances — rgg2d / rgg3d (streamed skagen generators),
a scipy Delaunay triangulation, and an fe_ocean-class triangulated FE
grid (BASELINE.json configs[3] names fe_ocean; the Walshaw archive is
unreachable offline) — runs the reference binary and this framework on
the SAME graphs, and appends rows to docs/recorded_configs.jsonl.

Usage:
    python scripts/record_nonrmat.py [instance ...]   # default: all
    instances: rgg2d rgg3d delaunay fe

The reference binary (built once from /root/reference):
    cmake -S /root/reference -B /tmp/kmp_build -G Ninja \
        -DCMAKE_BUILD_TYPE=Release -DKAMINPAR_BUILD_APPS=ON
    ninja -C /tmp/kmp_build KaMinParApp
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

BINARY = "/tmp/kmp_build/apps/KaMinPar"
OUT = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "recorded_configs.jsonl")
CACHE_DIR = "/tmp/nonrmat_graphs"

# (name, k, eps, preset, binary_seeds)
INSTANCES = {
    # BASELINE.md quality bar: cut within 3% of the CPU baseline
    "rgg2d": dict(k=16, eps=0.03, preset="default"),
    "rgg3d": dict(k=16, eps=0.03, preset="default"),
    "delaunay": dict(k=16, eps=0.03, preset="default"),
    # the fe_ocean-class config: k=32 with FM refinement (strong preset)
    "fe": dict(k=32, eps=0.03, preset="strong"),
}
SEEDS = (1, 2)


def build_graph(name: str):
    from kaminpar_tpu.graphs.factories import make_delaunay, make_fe_grid
    from kaminpar_tpu.io.skagen import hostgraph_from_stream, streamed

    if name == "rgg2d":
        return hostgraph_from_stream(
            streamed("rgg2d;n=1048576;avg_degree=8;seed=1", num_chunks=8)
        ), "rgg2d n=2^20 avg_degree=8 seed=1 (skagen)"
    if name == "rgg3d":
        return hostgraph_from_stream(
            streamed("rgg3d;n=1048576;avg_degree=8;seed=1", num_chunks=8)
        ), "rgg3d n=2^20 avg_degree=8 seed=1 (skagen)"
    if name == "delaunay":
        return make_delaunay(1 << 20, seed=1), (
            "delaunay n=2^20 seed=1 (scipy triangulation of uniform points)"
        )
    if name == "fe":
        return make_fe_grid(1024, 1024), (
            "fe-grid 1024x1024 triangulated (fe_ocean-class FE substitute)"
        )
    raise SystemExit(f"unknown instance {name}")


def graph_path(name: str, host) -> str:
    from kaminpar_tpu.io import write_metis

    os.makedirs(CACHE_DIR, exist_ok=True)
    path = os.path.join(CACHE_DIR, f"{name}.metis")
    if not os.path.exists(path):
        write_metis(host, path)
    return path


def run_binary(path: str, k: int, eps: float, seed: int):
    out = subprocess.run(
        [BINARY, path, "-k", str(k), "-e", str(eps), "-s", str(seed),
         "-t", "8"],
        capture_output=True, text=True, check=True,
    ).stdout
    m = re.search(r"Edge cut:\s*(\d+)", out)
    if m is None:
        sys.stderr.write(out)
        raise SystemExit("could not parse reference edge cut")
    t = re.search(r"\|- Partitioning: \.+ ([0-9.]+) s", out)
    return int(m.group(1)), (float(t.group(1)) if t else None)


def run_ours(host, k: int, eps: float, preset: str, seed: int):
    import jax

    from kaminpar_tpu.graphs.host import host_partition_metrics
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    p = KaMinPar(preset)
    p.set_output_level(OutputLevel.QUIET)
    t0 = time.perf_counter()
    part = p.set_graph(host).compute_partition(k=k, epsilon=eps, seed=seed)
    wall = time.perf_counter() - t0
    met = host_partition_metrics(host, part, k)
    return int(met["cut"]), float(met["imbalance"]), wall, jax.devices()[
        0
    ].platform


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    binary_only = "--binary-only" in sys.argv
    names = args or list(INSTANCES)
    ref_cache = os.path.join(CACHE_DIR, "reference_cuts.json")
    refs = {}
    if os.path.exists(ref_cache):
        with open(ref_cache) as f:
            refs = json.load(f)
    for name in names:
        cfg = INSTANCES[name]
        print(f"=== {name}: generating ===", flush=True)
        host, desc = build_graph(name)
        print(f"    n={host.n} m={host.m // 2}", flush=True)
        path = graph_path(name, host)

        ref_key = f"{name}:k{cfg['k']}:e{cfg['eps']}:s{list(SEEDS)}"
        if ref_key in refs:
            ref_best, ref_wall = refs[ref_key]
        else:
            ref_best, ref_wall = None, None
            for s in SEEDS:
                cut, wall = run_binary(path, cfg["k"], cfg["eps"], s)
                print(
                    f"    reference seed {s}: cut={cut} wall={wall}",
                    flush=True,
                )
                if ref_best is None or cut < ref_best:
                    ref_best, ref_wall = cut, wall
            refs[ref_key] = [ref_best, ref_wall]
            with open(ref_cache, "w") as f:
                json.dump(refs, f)
        if binary_only:
            print(f"    reference best: {ref_best} ({ref_wall}s)", flush=True)
            continue

        best = None
        for s in SEEDS:
            cut, imb, wall, platform = run_ours(
                host, cfg["k"], cfg["eps"], cfg["preset"], s
            )
            print(
                f"    ours seed {s}: cut={cut} imb={imb:.4f} wall={wall:.1f}",
                flush=True,
            )
            if best is None or cut < best["cut"]:
                best = dict(cut=cut, imbalance=imb, wall_s=round(wall, 1),
                            platform=platform)

        row = {
            "config": f"nonrmat-{name}",
            "graph": desc,
            "n": host.n,
            "m_undirected": host.m // 2,
            "k": cfg["k"],
            "epsilon": cfg["eps"],
            "preset": cfg["preset"],
            "seeds": list(SEEDS),
            "cut": best["cut"],
            "imbalance": best["imbalance"],
            "wall_s": best["wall_s"],
            "platform": best["platform"],
            "reference_cut_best": ref_best,
            "reference_wall_s": ref_wall,
            "cut_vs_reference": round(best["cut"] / ref_best, 4),
        }
        with open(OUT, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(f"    recorded: ours/ref = {row['cut_vs_reference']}", flush=True)


if __name__ == "__main__":
    main()
