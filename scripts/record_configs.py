#!/usr/bin/env python
"""Record the BASELINE.json headline configs that previous rounds never
exercised, on real hardware:

  * configs[3] analog — k=32 with FM refinement enabled (strong preset).
    The Walshaw fe_ocean graph itself is unreachable offline (zero
    egress); the bench RMAT at the same scale class substitutes, and the
    substitution is recorded in the output.
  * configs[4] — compressed-graph mode, k=128, deep multilevel on the
    10M-edge graph (TeraPart v2 codec), with the compression ratio.
  * large-k — k=4096 on the 10M-edge graph (largek preset, no dense
    (n, k) structures), with wall time and peak device memory.

Each run appends one JSON line to docs/recorded_configs.jsonl.
Usage: python scripts/record_configs.py [fe_ocean|compressed128|largek4096]
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")

OUT = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "recorded_configs.jsonl")


def record(entry: dict) -> None:
    entry["recorded_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(OUT, "a") as f:
        f.write(json.dumps(entry) + "\n")
    print(json.dumps(entry), flush=True)


def run(name: str, preset: str, n: int, m: int, gen_seed: int, k: int,
        compressed: bool = False, seed: int = 1) -> None:
    import numpy as np

    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.graphs.host import host_partition_metrics
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    entry = {
        "config": name,
        "graph": f"rmat n={n} m={m} seed={gen_seed}",
        "preset": preset,
        "k": k,
        "eps": 0.03,
        "seed": seed,
    }
    if compressed:
        # TeraPart compute parity: generation + compression run in a
        # SUBPROCESS that writes only the compressed file, so THIS
        # process (whose ru_maxrss is recorded) never holds the flat
        # CSR — it loads compressed, partitions through the chunked
        # device upload, and measures the cut with chunked decodes.
        import subprocess
        import tempfile

        from kaminpar_tpu.graphs.compressed import (
            compressed_partition_metrics,
        )
        from kaminpar_tpu.io import load_compressed

        # np.savez appends .npz to extensionless-or-foreign suffixes
        path = os.path.join(tempfile.gettempdir(),
                            f"rmat_{n}_{m}_{gen_seed}.kcg.npz")
        if not os.path.exists(path):
            code = (
                "import sys; sys.path.insert(0, %r)\n"
                "from kaminpar_tpu.graphs.factories import make_rmat\n"
                "from kaminpar_tpu.graphs.compressed import compress_host_graph\n"
                "from kaminpar_tpu.io import write_compressed\n"
                "write_compressed(%r, compress_host_graph("
                "make_rmat(%d, %d, seed=%d)))\n"
            ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 path[: -len(".npz")], n, m, gen_seed)
            subprocess.run([sys.executable, "-c", code], check=True)
        cg = load_compressed(path)
        entry["codec"] = cg.codec
        entry["compression_ratio"] = round(cg.compression_ratio(), 2)
        entry["compressed_mb"] = cg.memory_bytes() // (1 << 20)
        p = KaMinPar(preset)
        p.set_output_level(OutputLevel.QUIET)
        t0 = time.perf_counter()
        part = p.set_graph(cg).compute_partition(k=k, epsilon=0.03,
                                                 seed=seed)
        entry["wall_s"] = round(time.perf_counter() - t0, 1)
        entry["decoded_on_host"] = getattr(p, "_decoded", None) is not None
        res = compressed_partition_metrics(cg, part, k)
        nw = cg.node_weight_array()
    else:
        host = make_rmat(n, m, seed=gen_seed)
        p = KaMinPar(preset)
        p.set_output_level(OutputLevel.QUIET)
        t0 = time.perf_counter()
        part = p.set_graph(host).compute_partition(k=k, epsilon=0.03,
                                                   seed=seed)
        entry["wall_s"] = round(time.perf_counter() - t0, 1)
        res = host_partition_metrics(host, part, k)
        nw = host.node_weight_array()
    cap = (1 + 0.03) * np.ceil(nw.sum() / k)
    entry["cut"] = int(res["cut"])
    entry["imbalance"] = round(float(res["imbalance"]), 5)
    entry["feasible"] = bool(res["block_weights"].max() <= cap)
    entry["peak_host_rss_mb"] = (
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
    )
    record(entry)


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("fe_ocean", "all"):
        # configs[3] analog: FM-enabled k=32.  fe_ocean (Walshaw archive)
        # is not fetchable offline; the medium bench RMAT is the same
        # size class (fe_ocean: n=143k m=410k)
        run("configs[3]-analog fe_ocean-substitute k=32 FM (strong)",
            "strong", 1 << 17, 420_000, 77, 32)
    if which in ("compressed128", "all"):
        run("configs[4] compressed-mode k=128 deep", "terapart",
            1 << 20, 10_000_000, 7, 128, compressed=True)
    if which in ("largek4096", "all"):
        run("large-k k=4096 (largek preset)", "largek",
            1 << 20, 10_000_000, 7, 4096)


if __name__ == "__main__":
    main()
