#!/usr/bin/env python
"""Microbenchmark the TPU primitive ops the kernels are built from.

Honest timing on the axon remote backend: every measurement forces a
scalar readback (block_until_ready does not reliably block there), takes
the MINIMUM of `reps` runs (steady state), and subtracts nothing — the
dispatch floor is part of what a kernel pays.

Usage: python scripts/microbench_ops.py [log2_m] [log2_n]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

import jax.numpy as jnp
import numpy as np
from jax import lax

LOG_M = int(sys.argv[1]) if len(sys.argv) > 1 else 24
LOG_N = int(sys.argv[2]) if len(sys.argv) > 2 else 20
M = 1 << LOG_M
N = 1 << LOG_N
REPS = 4


def timeit(name, fn, *args):
    fn_j = jax.jit(fn)
    out = fn_j(*args)  # compile
    int(jnp.sum(jax.tree_util.tree_leaves(out)[0].reshape(-1)[:1]))
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn_j(*args)
        int(jnp.sum(jax.tree_util.tree_leaves(out)[0].reshape(-1)[:1]))
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({"op": name, "ms": round(best * 1e3, 1),
                      "ns_per_elem": round(best * 1e9 / M, 2)}), flush=True)
    return best


def main():
    rng = np.random.RandomState(0)
    src = jnp.asarray(np.sort(rng.randint(0, N, M)).astype(np.int32))
    dst = jnp.asarray(rng.randint(0, N, M).astype(np.int32))
    w = jnp.asarray(rng.randint(1, 100, M).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, N, N).astype(np.int32))
    ew = jnp.asarray(rng.randint(1, 100, M).astype(np.int32))
    print(f"== M=2^{LOG_M} ({M}), N=2^{LOG_N} ({N}) ==", flush=True)

    timeit("noop_scalar", lambda x: jnp.sum(x[:8]), w)
    timeit("elementwise_add", lambda a, b: a + b, w, ew)
    timeit("cumsum", jnp.cumsum, w)
    timeit("gather_m_from_n", lambda l, d: l[d], labels, dst)
    timeit("gather_m_from_n_sorted_idx", lambda l, s: l[s], labels, src)
    timeit(
        "segment_sum_to_n",
        lambda v, s: jax.ops.segment_sum(v, s, num_segments=N), w, src,
    )
    timeit(
        "segment_sum_to_n_unsorted",
        lambda v, d: jax.ops.segment_sum(v, d, num_segments=N), w, dst,
    )
    k = 16
    flat16 = (src * k + (dst % k)).astype(jnp.int32)
    timeit(
        "segment_sum_flat_nk16",
        lambda v, f: jax.ops.segment_sum(v, f, num_segments=N * k), w, flat16,
    )
    timeit("sort_1key", lambda a: lax.sort((a,), num_keys=1), dst)
    timeit(
        "sort_2key_1val",
        lambda a, b, c: lax.sort((a, b, c), num_keys=2), src, dst, w,
    )
    timeit(
        "sort_3key_1val",
        lambda a, b, c, d: lax.sort((a, b, c, d), num_keys=3),
        src, dst, w, ew,
    )
    timeit(
        "scatter_set_m_to_m",
        lambda v, i: jnp.zeros(M, jnp.int32).at[i].set(v),
        w, jnp.asarray(rng.permutation(M).astype(np.int32)),
    )


if __name__ == "__main__":
    main()
