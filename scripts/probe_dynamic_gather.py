#!/usr/bin/env python
"""Probe: is Mosaic's tpu.dynamic_gather fast on tall tables?

JAX 0.9.0 lowers jnp.take_along_axis(x, idx, axis=0) inside Pallas TPU
kernels to tpu.dynamic_gather when x.shape == idx.shape (2D).  Semantics:
out[s, l] = x[idx[s, l], l] — a per-LANE gather across sublanes.

If this runs near streaming speed for tall x (S in the thousands), the
LP/Jet `labels[dst]` gather (12.5 ns/index via XLA, 0.09% of HBM peak)
can be rebuilt as:
  1. one-time (per graph level, indices are static): route each flat
     index f to lane f % 128, pad lanes to equal height;
  2. per round: k grid steps of table-shaped dynamic_gather from the
     VMEM-resident table;
  3. no un-permute — downstream rating engines are order-agnostic
     (segment_sum / sort by src), so src rides the same static routing.

Usage: python scripts/probe_dynamic_gather.py [cpu|tpu]
"""

from __future__ import annotations

import functools
import json
import sys
import time

import os

if len(sys.argv) > 1 and sys.argv[1] == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

L = 128


def _kernel_axis0(table_ref, idx_ref, out_ref):
    out_ref[...] = jnp.take_along_axis(table_ref[...], idx_ref[...], axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def lane_gather(table, idx, interpret=False):
    """out[c, s, l] = table[idx[c, s, l], l] for each chunk c."""
    S = table.shape[0]
    C = idx.shape[0] // S
    idx2 = idx.reshape(C, S, L)
    return pl.pallas_call(
        _kernel_axis0,
        grid=(C,),
        in_specs=[
            pl.BlockSpec((S, L), lambda c: (0, 0)),  # table resident
            pl.BlockSpec((None, S, L), lambda c: (c, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, S, L), lambda c: (c, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((C, S, L), table.dtype),
        interpret=interpret,
    )(table, idx2)


def check_correct(S, interpret):
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randint(0, 1 << 30, (S, L)).astype(np.int32))
    idx = jnp.asarray(rng.randint(0, S, (2 * S, L)).astype(np.int32))
    got = np.asarray(lane_gather(table, idx, interpret=interpret))
    want = np.take_along_axis(
        np.asarray(table), np.asarray(idx).reshape(2 * S, L), axis=0
    ).reshape(2, S, L)
    ok = np.array_equal(got, want)
    print(json.dumps({"probe": f"correct_S{S}", "ok": bool(ok)}), flush=True)
    return ok


def bench(S, log_m):
    M = 1 << log_m
    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.randint(0, 1 << 30, (S, L)).astype(np.int32))
    idx = jnp.asarray(rng.randint(0, S, (M // L, L)).astype(np.int32))
    out = lane_gather(table, idx)
    int(jnp.sum(out.reshape(-1)[:1]))
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        out = lane_gather(table, idx)
        int(jnp.sum(out.reshape(-1)[:1]))
        best = min(best, time.perf_counter() - t0)
    print(
        json.dumps(
            {
                "probe": f"lane_gather_S{S}_M2^{log_m}",
                "ms": round(best * 1e3, 2),
                "ns_per_index": round(best * 1e9 / M, 3),
            }
        ),
        flush=True,
    )


def bench_xla_baseline(log_m, log_n):
    M, N = 1 << log_m, 1 << log_n
    rng = np.random.RandomState(2)
    labels = jnp.asarray(rng.randint(0, 1 << 30, N).astype(np.int32))
    dst = jnp.asarray(rng.randint(0, N, M).astype(np.int32))
    f = jax.jit(lambda l, d: l[d])
    out = f(labels, dst)
    int(jnp.sum(out[:1]))
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        out = f(labels, dst)
        int(jnp.sum(out[:1]))
        best = min(best, time.perf_counter() - t0)
    print(
        json.dumps(
            {
                "probe": f"xla_gather_M2^{log_m}_N2^{log_n}",
                "ms": round(best * 1e3, 2),
                "ns_per_index": round(best * 1e9 / M, 3),
            }
        ),
        flush=True,
    )


def bench_lane_gather(log_m, log_n):
    """The real module: plan build + routed gather at the hot-op shape."""
    sys.path.insert(0, "/root/repo")
    from kaminpar_tpu.ops.lane_gather import build_gather_plan, lane_gather

    M, N = 1 << log_m, 1 << log_n
    rng = np.random.RandomState(3)
    idx = jnp.asarray(rng.randint(0, N, M).astype(np.int32))
    table = jnp.asarray(rng.randint(0, 1 << 30, N).astype(np.int32))
    t0 = time.perf_counter()
    plan = build_gather_plan(idx, N)
    int(jnp.sum(plan.q.reshape(-1)[:1]))
    plan_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    plan = build_gather_plan(idx, N)
    int(jnp.sum(plan.q.reshape(-1)[:1]))
    plan_warm = time.perf_counter() - t0
    out = lane_gather(table, plan)
    got = np.asarray(out)
    inv = np.asarray(plan.inv)
    ok = inv >= 0
    correct = bool(
        np.array_equal(got[ok], np.asarray(table)[np.asarray(idx)[inv[ok]]])
    )
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        out = lane_gather(table, plan)
        int(jnp.sum(out[:1]))
        best = min(best, time.perf_counter() - t0)
    print(
        json.dumps(
            {
                "probe": f"lane_gather_module_M2^{log_m}_N2^{log_n}",
                "correct": correct,
                "ms": round(best * 1e3, 2),
                "ns_per_index": round(best * 1e9 / M, 3),
                "routed_slots": plan.num_slots,
                "pad_overhead": round(plan.num_slots / M - 1, 3),
                "plan_build_cold_s": round(plan_cold, 2),
                "plan_build_warm_s": round(plan_warm, 3),
            }
        ),
        flush=True,
    )


def main():
    on_cpu = jax.devices()[0].platform == "cpu"
    print(f"platform: {jax.devices()[0].platform}", flush=True)
    if on_cpu:
        for S in (8, 512):
            check_correct(S, interpret=True)
        return
    # device: correctness at three heights, then timing
    for S in (8, 512, 8192):
        if not check_correct(S, interpret=False):
            print("INCORRECT — abort timing", flush=True)
            return
    bench_xla_baseline(24, 20)
    for S in (512, 2048, 8192):
        bench(S, 24)
    bench_lane_gather(24, 20)
    bench_lane_gather(24, 22)


if __name__ == "__main__":
    main()
