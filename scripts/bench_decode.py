#!/usr/bin/env python
"""Measure v2-codec decode bandwidth (the streamvbyte.h parity probe).

The reference ships an SSSE3 StreamVByte batch decoder
(kaminpar-common/graph_compression/streamvbyte.h); codec2.cpp now takes
the same shuffle-table SIMD path for residual groups.  This records
decode throughput in edges/s and output GB/s on the 10M-edge bench
graph (run solo — the box has one core).

Usage: python scripts/bench_decode.py [log2_n] [m]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from kaminpar_tpu import native
    from kaminpar_tpu.graphs.factories import make_rmat

    if not native.available():
        raise SystemExit("native library unavailable")
    log_n = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    m = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000_000
    g = make_rmat(1 << log_n, m, seed=7)
    xadj = np.ascontiguousarray(g.xadj, dtype=np.int64)
    adjncy = np.ascontiguousarray(g.adjncy, dtype=np.int32)

    enc = native.encode_v2(xadj, adjncy)
    data, offsets = enc
    out = np.empty(len(adjncy), dtype=np.int32)
    lib = native.get_lib()
    n = len(xadj) - 1

    lib.kmp_decode_v2(n, xadj, offsets, data, out)  # warm
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        lib.kmp_decode_v2(n, xadj, offsets, data, out)
        best = min(best, time.perf_counter() - t0)

    # decoded output must round-trip (interval-members-first emit order:
    # compare as per-row sorted sets)
    ok = True
    for u in (0, 1, n // 2, n - 1):
        lo, hi = int(xadj[u]), int(xadj[u + 1])
        ok &= sorted(out[lo:hi].tolist()) == sorted(adjncy[lo:hi].tolist())

    edges = len(adjncy)
    print(
        json.dumps(
            {
                "probe": "v2_decode",
                "edges": edges,
                "compressed_bytes": int(len(data)),
                "ratio": round(edges * 4 / len(data), 2),
                "decode_s": round(best, 3),
                "edges_per_s_M": round(edges / best / 1e6, 1),
                "out_GB_s": round(edges * 4 / best / 1e9, 2),
                "roundtrip_ok": bool(ok),
            }
        )
    )


if __name__ == "__main__":
    main()
