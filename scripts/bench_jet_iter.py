#!/usr/bin/env python
"""Standalone Jet-iteration cost at the 10M-graph fine shape (warm)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
import jax.numpy as jnp
import numpy as np
from kaminpar_tpu.graphs.csr import device_graph_from_host
from kaminpar_tpu.graphs.factories import make_rmat
from kaminpar_tpu.context import JetRefinementContext
from kaminpar_tpu.ops.jet import jet_refine

host = make_rmat(1 << 20, 10_000_000, seed=7)
g = device_graph_from_host(host)
int(jnp.sum(g.src[:1]))
k = 16
rng = np.random.default_rng(1)
p0 = np.zeros(g.n_pad, np.int32)
p0[: host.n] = rng.integers(0, k, host.n)
p0 = jnp.asarray(p0)
nw = host.node_weight_array()
cap = jnp.full(k, int(1.03 * np.ceil(nw.sum() / k)), dtype=jnp.int32)
ctx = JetRefinementContext(num_iterations=8, num_fruitless_iterations=0)
for rep in range(3):
    t0 = time.perf_counter()
    out = jet_refine(g, p0, k, cap, jnp.int32(3), ctx, level=0)
    int(jnp.sum(out[:1]))
    dt = time.perf_counter() - t0
    print(f"rep{rep}: 8 iters = {dt:.2f}s  ({dt/8*1000:.0f} ms/iter)", flush=True)
