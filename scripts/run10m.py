#!/usr/bin/env python
"""Plain (unprofiled) end-to-end 10M-edge runs — the honest wall-clock.
Usage: python scripts/run10m.py [reps] [preset] [fruitless_override]"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
import numpy as np

reps = int(sys.argv[1]) if len(sys.argv) > 1 else 2
preset = sys.argv[2] if len(sys.argv) > 2 else "default"
fruitless = int(sys.argv[3]) if len(sys.argv) > 3 else 0

from kaminpar_tpu.graphs.factories import make_rmat
from kaminpar_tpu.graphs.host import host_partition_metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.utils.logger import OutputLevel

host = make_rmat(1 << 20, 10_000_000, seed=7)
for rep in range(reps):
    p = KaMinPar(preset)
    if fruitless:
        p.ctx.refinement.jet.num_fruitless_iterations = fruitless
    p.set_output_level(OutputLevel.QUIET)
    t0 = time.perf_counter()
    part = p.set_graph(host).compute_partition(k=16, epsilon=0.03, seed=1)
    dt = time.perf_counter() - t0
    m = host_partition_metrics(host, part, 16)
    print(f"rep{rep}: {dt:.1f}s cut={m['cut']} imb={m['imbalance']:.4f}",
          flush=True)
