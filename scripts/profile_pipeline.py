#!/usr/bin/env python
"""Per-call phase profiler for the deep pipeline.

Wraps the hot entry points (lp_cluster, contract_clustering, jet_refine,
lp_refine, balancers, extend_partition, host IP) with readback-synced
wall-clock timing and shape logging, then runs a full partition.  On the
axon remote backend `block_until_ready` does not reliably block, so every
wrapper forces a scalar readback before reading the clock.

Usage:
  python scripts/profile_pipeline.py [gen-spec] [k] [preset]
  (defaults: rmat;n=1048576;m=10000000;seed=7  16  default)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

EVENTS = []


def _sync(x):
    try:
        if isinstance(x, tuple):
            x = x[0]
        if hasattr(x, "graph"):  # CoarseGraph
            int(jnp.sum(x.graph.src[:1]))
        elif isinstance(x, jax.Array):
            int(jnp.sum(x.reshape(-1)[:1]))
    except Exception:
        pass


def wrap(mod, name, tag, shape_of=None):
    fn = getattr(mod, name)

    def wrapper(*args, **kwargs):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        _sync(out)
        dt = time.perf_counter() - t0
        info = {"phase": tag, "dt": round(dt, 3)}
        if shape_of is not None:
            try:
                info.update(shape_of(*args, **kwargs))
            except Exception:
                pass
        EVENTS.append(info)
        print(json.dumps(info), flush=True)
        return out

    wrapper.__wrapped__ = fn
    setattr(mod, name, wrapper)
    return wrapper


def graph_shape(graph, *a, **k):
    return {"n_pad": int(graph.n_pad), "m_pad": int(graph.src.shape[0])}


def main():
    spec = sys.argv[1] if len(sys.argv) > 1 else "rmat;n=1048576;m=10000000;seed=7"
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    preset = sys.argv[3] if len(sys.argv) > 3 else "default"

    from kaminpar_tpu.graphs.factories import generate
    from kaminpar_tpu.ops import contraction as contraction_mod
    from kaminpar_tpu.ops import jet as jet_mod
    from kaminpar_tpu.ops import lp as lp_mod
    from kaminpar_tpu.ops import balancer as bal_mod
    from kaminpar_tpu.partitioning import coarsener as coarsener_mod
    from kaminpar_tpu.partitioning import deep as deep_mod
    from kaminpar_tpu.partitioning import refiner as refiner_mod
    from kaminpar_tpu import initial as initial_mod

    # --- wrap ops, then rebind the names modules imported at top level ---
    wrap(lp_mod, "lp_cluster", "lp_cluster", graph_shape)
    wrap(lp_mod, "lp_refine", "lp_refine", graph_shape)
    wrap(contraction_mod, "contract_clustering", "contract", graph_shape)
    wrap(jet_mod, "jet_refine", "jet", graph_shape)
    wrap(
        jet_mod,
        "_jet_chunk",
        "jet_chunk",
        lambda graph, *a, **k: {
            "n_pad": int(graph.n_pad),
            "m_pad": int(graph.src.shape[0]),
        },
    )
    wrap(bal_mod, "overload_balance", "overload_bal", graph_shape)
    wrap(bal_mod, "underload_balance", "underload_bal", graph_shape)
    coarsener_mod.lp_cluster = lp_mod.lp_cluster
    coarsener_mod.contract_clustering = contraction_mod.contract_clustering
    refiner_mod.lp_refine = lp_mod.lp_refine
    refiner_mod.balancer_ops = bal_mod

    # host-side phases
    orig_extend = deep_mod.DeepMultilevelPartitioner._extend_partition

    def extend_wrapper(self, dgraph, partition, spans, next_k, rng):
        t0 = time.perf_counter()
        out = orig_extend(self, dgraph, partition, spans, next_k, rng)
        _sync(out[0])
        info = {
            "phase": "extend_partition",
            "dt": round(time.perf_counter() - t0, 3),
            "n_pad": int(dgraph.n_pad),
            "next_k": next_k,
        }
        EVENTS.append(info)
        print(json.dumps(info), flush=True)
        return out

    deep_mod.DeepMultilevelPartitioner._extend_partition = extend_wrapper

    orig_bip = initial_mod.InitialMultilevelBipartitioner.bipartition

    def bip_wrapper(self, graph, max_w, rng):
        t0 = time.perf_counter()
        out = orig_bip(self, graph, max_w, rng)
        info = {
            "phase": "host_ip",
            "dt": round(time.perf_counter() - t0, 3),
            "n": int(graph.n),
        }
        EVENTS.append(info)
        print(json.dumps(info), flush=True)
        return out

    initial_mod.InitialMultilevelBipartitioner.bipartition = bip_wrapper
    deep_mod.InitialMultilevelBipartitioner = initial_mod.InitialMultilevelBipartitioner

    import kaminpar_tpu as ktp

    host = generate(spec)
    t0 = time.perf_counter()
    part = (
        ktp.KaMinPar(preset)
        .set_graph(host)
        .compute_partition(k=k, epsilon=0.03, seed=1)
    )
    total = time.perf_counter() - t0

    from kaminpar_tpu.graphs.host import host_partition_metrics

    m = host_partition_metrics(host, part, k)
    by_phase = {}
    for e in EVENTS:
        by_phase.setdefault(e["phase"], [0.0, 0])
        by_phase[e["phase"]][0] += e["dt"]
        by_phase[e["phase"]][1] += 1
    print("== SUMMARY ==", flush=True)
    print(
        json.dumps(
            {
                "total_s": round(total, 1),
                "cut": int(m["cut"]),
                "imbalance": float(m["imbalance"]),
                "phases": {
                    p: {"s": round(v[0], 1), "calls": v[1]}
                    for p, v in sorted(
                        by_phase.items(), key=lambda kv: -kv[1][0]
                    )
                },
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
