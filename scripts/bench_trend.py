#!/usr/bin/env python
"""Render (and sanity-check) the recorded BENCH trajectory.

The harness records one `BENCH_rNN.json` per round: the bench.py exit
status, output tail, and the parsed BENCH line (which, since the
telemetry layer landed, embeds the schema-validated run report).  This
tool turns the checked-in trajectory into a table — cut, vs_baseline,
wall seconds, and the compile split when a round carries a v2 report —
so "did round N regress round N-1" is a read, not an archaeology dig.

Usage:
  python scripts/bench_trend.py [--dir REPO] [--json]
  python scripts/bench_trend.py --check     # CI: structural validation

`--check` exits non-zero when a recorded round is malformed (unreadable
JSON, rc==0 without a parsed BENCH line, parsed line missing the metric
fields, a schema-v5 report without its `perf` section) — cut/wall and
the perf-observatory columns' movements between rounds (hbm_util,
pad_waste, p95_ms) are PRINTED, not gated: rounds run on different code
by design, and the per-PR regression gate is `telemetry.diff` on
like-for-like reports (scripts/check_all.sh), which DOES gate serving
hit-rate and served-count regressions.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REQUIRED_PARSED_KEYS = ("metric", "value", "unit")


def load_rounds(repo: str) -> List[Tuple[str, dict]]:
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_r*.json")))
    return [(p, json.load(open(p))) for p in paths]


def check_round(path: str, entry: Any) -> List[str]:
    errors: List[str] = []
    name = os.path.basename(path)
    if not isinstance(entry, dict):
        return [f"{name}: not a JSON object"]
    for key in ("n", "cmd", "rc"):
        if key not in entry:
            errors.append(f"{name}: missing key {key!r}")
    rc = entry.get("rc")
    parsed = entry.get("parsed")
    if rc == 0:
        if not isinstance(parsed, dict):
            errors.append(f"{name}: rc==0 but no parsed BENCH line")
        else:
            for key in REQUIRED_PARSED_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: parsed BENCH line missing {key!r}"
                    )
            report = parsed.get("report")
            if report is not None and (
                not isinstance(report, dict)
                or "schema_version" not in report
            ):
                errors.append(
                    f"{name}: embedded report lacks schema_version"
                )
            elif (
                isinstance(report, dict)
                and isinstance(report.get("schema_version"), int)
                and report["schema_version"] >= 5
                and "perf" not in report
            ):
                errors.append(
                    f"{name}: schema-v5 report carries no perf section"
                )
    return errors


def _row(path: str, entry: dict) -> Dict[str, Any]:
    parsed = entry.get("parsed") or {}
    report = parsed.get("report") or {}
    compile_totals = report.get("compile", {}).get("totals", {})
    # v4 reports from a serving run carry the bounded-cache hit rate —
    # the first-class serving metric alongside cut/seconds (rounds
    # without a serving section show "-")
    serving = report.get("serving") or {}
    cache_hit = (serving.get("cache") or {}).get("hit_rate")
    # v5 reports carry the perf observatory's headline columns: overall
    # achieved-vs-peak HBM utilization, overall padding waste, and (for
    # serve-mode rounds) the caller-observed p95 latency
    perf_totals = (report.get("perf") or {}).get("totals") or {}
    p95_ms = (
        ((serving.get("latency") or {}).get("phases") or {})
        .get("total", {}).get("p95_ms")
    )
    return {
        "round": os.path.basename(path),
        "rc": entry.get("rc"),
        "cut": parsed.get("value"),
        "vs_baseline": parsed.get("vs_baseline"),
        "total_s": parsed.get("total_seconds"),
        "coarsening_s": parsed.get("lp_coarsening_seconds"),
        "platform": parsed.get("platform"),
        "compile_s": compile_totals.get("compile_s"),
        "cache_hit": cache_hit,
        "hbm_util": parsed.get("hbm_util", perf_totals.get("hbm_util")),
        "pad_waste": parsed.get(
            "pad_waste", perf_totals.get("pad_waste")
        ),
        "p95_ms": p95_ms,
        "schema": report.get("schema_version"),
    }


def _fmt(v: Optional[Any]) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


def render(rows: List[Dict[str, Any]]) -> str:
    cols = ("round", "rc", "cut", "vs_baseline", "total_s",
            "coarsening_s", "compile_s", "cache_hit", "hbm_util",
            "pad_waste", "p95_ms", "platform", "schema")
    table = [cols] + [tuple(_fmt(r[c]) for c in cols) for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = [
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row))
        for row in table
    ]
    # movement annotations between consecutive parsed rounds
    prev = None
    for r in rows:
        if prev and r["cut"] and prev["cut"]:
            delta = 100.0 * (r["cut"] - prev["cut"]) / prev["cut"]
            if abs(delta) >= 5.0:
                lines.append(
                    f"note: {prev['round']} -> {r['round']} cut moved "
                    f"{delta:+.1f}%"
                )
        if prev:
            # perf-observatory movement notes (printed, never gated —
            # see the module docstring's gating rationale)
            for col, floor in (("hbm_util", 0.01), ("pad_waste", 0.05),
                               ("p95_ms", None)):
                a, b = prev.get(col), r.get(col)
                if a is None or b is None:
                    continue
                if col == "p95_ms":
                    if a > 0 and abs(b - a) / a >= 0.5:
                        lines.append(
                            f"note: {prev['round']} -> {r['round']} "
                            f"p95_ms moved {a} -> {b}"
                        )
                elif abs(b - a) >= floor:
                    lines.append(
                        f"note: {prev['round']} -> {r['round']} "
                        f"{col} moved {a} -> {b}"
                    )
        if r["cut"] is not None:
            prev = r
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render / validate the BENCH_r*.json trajectory"
    )
    ap.add_argument(
        "--dir",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="repo root holding BENCH_r*.json (default: this repo)",
    )
    ap.add_argument("--json", action="store_true", help="emit rows as JSON")
    ap.add_argument(
        "--check", action="store_true",
        help="CI mode: exit non-zero on structurally malformed rounds",
    )
    args = ap.parse_args(argv)

    try:
        rounds = load_rounds(args.dir)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not rounds:
        print(f"no BENCH_r*.json under {args.dir}", file=sys.stderr)
        return 0 if not args.check else 1

    errors: List[str] = []
    for path, entry in rounds:
        errors.extend(check_round(path, entry))
    rows = [_row(p, e) for p, e in rounds if isinstance(e, dict)]
    if args.json:
        print(json.dumps(rows))
    else:
        print(render(rows))
    if errors:
        for e in errors:
            print(f"TREND VIOLATION {e}", file=sys.stderr)
    if args.check:
        print(f"trend check: {len(rounds)} round(s), "
              f"{len(errors)} violation(s)")
        return 1 if errors else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
