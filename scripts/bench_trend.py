#!/usr/bin/env python
"""Render (and sanity-check) the recorded BENCH trajectory.

The harness records one `BENCH_rNN.json` per round: the bench.py exit
status, output tail, and the parsed BENCH line (which, since the
telemetry layer landed, embeds the schema-validated run report).  This
tool turns the checked-in trajectory into a table — cut, vs_baseline,
wall seconds, and the compile split when a round carries a v2 report —
so "did round N regress round N-1" is a read, not an archaeology dig.

Usage:
  python scripts/bench_trend.py [--dir REPO] [--json]
  python scripts/bench_trend.py --check     # CI: structural validation

`--check` exits non-zero when a recorded round is malformed (unreadable
JSON, rc==0 without a parsed BENCH line, parsed line missing the metric
fields, a schema-v5 report without its `perf` section) — the
perf-observatory columns' movements between rounds (hbm_util,
pad_waste, p95_ms) are PRINTED, not gated: rounds run on different code
by design, and the per-PR regression gate is `telemetry.diff` on
like-for-like reports (scripts/check_all.sh), which DOES gate serving
hit-rate and served-count regressions.

`--check` is ALSO the kernel regression gate (round 9): the LATEST
parsed round must keep `vs_baseline` above the cut floor (cuts are
platform-independent), must still carry every 10M-coverage key
(BENCH_r05 dropped them silently — presence is gated, null marks a
failed measurement), and — on accelerator rounds only, where walls are
meaningful — must keep `lp_coarsening_seconds` under the ceiling and
`hbm_util` above the utilization floor.  Floors are flags
(--cut-floor/--coarsening-ceiling/--hbm-util-floor) so a deliberate
re-baseline is an explicit diff, not a silent drift.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REQUIRED_PARSED_KEYS = ("metric", "value", "unit")

#: 10M-edge coverage keys every round from r06 on must carry (null =
#: the measurement failed; ABSENT = the bench silently lost coverage,
#: which is what r05 did and what this gate exists to catch), plus the
#: kernel-utilization probes.
LARGE_COVERAGE_KEYS = (
    "lp_coarsening_10m_seconds", "cut_10m", "feasible_10m",
    "vs_baseline_cut_10m", "util_gather_pct_hbm",
    "util_scatter_add_pct_hbm", "util_stream_cumsum_pct_hbm",
)
#: Rounds BELOW this index predate the coverage contract (the gate
#: applies to rno >= LARGE_COVERAGE_SINCE, i.e. r06 onward).
LARGE_COVERAGE_SINCE = 6

#: Quality-attribution keys (round 11, telemetry/quality.py): the BENCH
#: line must always carry them from r06 on (same presence contract as
#: the 10M block — null marks a run without attribution, absence a
#: silent coverage loss).  Their VALUES are advisory only (see
#: --locked-frac-ceiling): the floor is relative to each run's own
#: final partition, so the fraction is a direction signal, not a gate.
QUALITY_COVERAGE_KEYS = ("coarsening_locked_frac",
                         "refinement_left_frac")

#: Out-of-core streaming keys (round 13, kaminpar_tpu/external/): the
#: BENCH line must always carry them from r06 on (null = the external
#: measurement was skipped/failed, absence = silent coverage loss of
#: the scale path — the r05 regression class).
EXTERNAL_COVERAGE_KEYS = ("external_seconds", "stream_overlap")

#: Supervised-serving key (round 14, resilience/supervisor.py): the
#: BENCH line must always carry it from r06 on (null = the supervised
#: batch was skipped/failed or the platform can't spawn workers,
#: absence = silent coverage loss of the containment boundary's
#: latency trend — the r05 regression class).
SUPERVISED_COVERAGE_KEYS = ("supervised_p95_ms",)

#: Dynamic-repartitioning keys (round 15, kaminpar_tpu/dynamic/): the
#: BENCH line must always carry them from r06 on (null = the dynamic
#: chain measurement was skipped/failed, absence = silent coverage
#: loss of the warm-repartition trend — the r05 regression class).
DYNAMIC_COVERAGE_KEYS = ("dynamic_warm_speedup", "dynamic_cut_drift")

#: Serving-throughput keys (round 16, fleet observatory): the BENCH
#: line must always carry them from r06 on (null = the supervised
#: batch was skipped/failed, absence = silent coverage loss of the
#: throughput trend — the r05 regression class).
THROUGHPUT_COVERAGE_KEYS = ("requests_per_second", "batch_occupancy")

#: Static-analysis key (round 17, tpulint v2): the BENCH line must
#: always carry the full-rule lint pass's wall from r06 on (null = the
#: lint run errored, absence = silent coverage loss of the commit
#: gate's own cost trend — the r05 regression class).
LINT_COVERAGE_KEYS = ("tpulint_seconds",)

#: Execution-ledger keys (round 19, telemetry/ledger.py): the BENCH
#: line must always carry them from r06 on (null = the report had no
#: ledger, absence = silent coverage loss of the launch-honesty and
#: transfer-bytes trends — the r05 regression class).  The transfer
#: VALUES are advisory (printed as a column, never gated); the honesty
#: of accelerator rounds IS gated — see _roofline_honesty_errors.
LEDGER_COVERAGE_KEYS = ("util_honest", "launches_total",
                        "transfer_bytes_per_phase")

#: Integrity-sentinel key (round 20, resilience/integrity.py): the
#: BENCH line must always carry the sentinel-overhead percentage from
#: r06 on (0.0 = the kill switch disabled the layer, absence = silent
#: coverage loss of the corruption-defense cost trend — the r05
#: regression class).  The VALUE is advisory only (printed as a
#: column, never gated): the < 3% dormancy budget is a test assertion
#: (tests/test_integrity.py), not a trend gate.
INTEGRITY_COVERAGE_KEYS = ("integrity_overhead_pct",)

#: Platforms whose wall/utilization figures are meaningful (the CPU
#: fallback's walls are smoke signals by repo doctrine — bench.py
#: stamps `platform` exactly so gates can tell).
ACCEL_PLATFORMS = ("tpu", "axon")

#: Dist-resilience coverage keys the MULTICHIP dryrun tail must carry
#: from r06 on (round 12, __graft_entry__.dryrun_multichip): the
#: kill-and-resume cut-identity probe and the agreed-OOM-ladder probe.
#: Same presence contract as the 10M block — absence means the dryrun
#: silently lost the coverage, which is the r05 regression class.
#: The comm-volume key (round 16): the dryrun tail must carry the
#: machine-readable per-run collective rollup from r06 on.
MULTICHIP_COVERAGE_KEYS = (
    "dist_resumable=", "dist_ladder=", "comm_bytes_total=",
)
MULTICHIP_COVERAGE_SINCE = 6


def load_multichip_rounds(repo: str) -> List[Tuple[str, dict]]:
    paths = sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json")))
    return [(p, json.load(open(p))) for p in paths]


def check_multichip_round(path: str, entry: Any) -> List[str]:
    """MULTICHIP_rNN structural + coverage validation: a successful
    (ok, not skipped) round from r06 on must carry the dist-resilience
    keys in its tail."""
    errors: List[str] = []
    name = os.path.basename(path)
    if not isinstance(entry, dict):
        return [f"{name}: not a JSON object"]
    rno = _round_number(name)
    if (
        rno is None
        or rno < MULTICHIP_COVERAGE_SINCE
        or not entry.get("ok")
        or entry.get("skipped")
    ):
        return errors
    tail = entry.get("tail") or ""
    for key in MULTICHIP_COVERAGE_KEYS:
        if key not in tail:
            errors.append(
                f"{name}: MULTICHIP coverage key {key!r} missing from "
                "the dryrun tail (r05 regression class — "
                "dryrun_multichip must emit it every round)"
            )
    return errors


def _roofline_honesty_errors(name: str, parsed: dict) -> List[str]:
    """Accelerator rounds with a v13+ embedded report must have a LIVE
    launch ledger: when every roofline row that reports hbm_util
    carries honest=false, the ledger recorded nothing and the recorded
    utilization trend silently degraded to compile-time lower bounds
    (KAMINPAR_TPU_LEDGER=0 on a recorded round, or the executable-call
    interception died).  Pre-v13 reports (no `honest` stamps) and
    CPU-fallback rounds are exempt."""
    report = parsed.get("report") or {}
    if not isinstance(report, dict):
        return []
    version = report.get("schema_version")
    if not isinstance(version, int) or version < 13:
        return []
    if parsed.get("platform") not in ACCEL_PLATFORMS:
        return []
    roof = (report.get("perf") or {}).get("roofline") or {}
    rows = [
        e for e in roof.values()
        if isinstance(e, dict) and e.get("hbm_util") is not None
    ]
    if rows and all(not e.get("honest") for e in rows):
        return [
            f"{name}: every roofline row is honest=false on an "
            "accelerator round — the launch ledger recorded nothing "
            "(dead interception or KAMINPAR_TPU_LEDGER=0 on a "
            "recorded round)"
        ]
    return []


def _round_number(name: str) -> Optional[int]:
    """BENCH_r07.json -> 7 (None for non-conforming names)."""
    stem = os.path.splitext(name)[0]
    digits = "".join(ch for ch in stem if ch.isdigit())
    return int(digits) if digits else None


def load_rounds(repo: str) -> List[Tuple[str, dict]]:
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_r*.json")))
    return [(p, json.load(open(p))) for p in paths]


def check_round(path: str, entry: Any) -> List[str]:
    errors: List[str] = []
    name = os.path.basename(path)
    if not isinstance(entry, dict):
        return [f"{name}: not a JSON object"]
    for key in ("n", "cmd", "rc"):
        if key not in entry:
            errors.append(f"{name}: missing key {key!r}")
    rc = entry.get("rc")
    parsed = entry.get("parsed")
    if rc == 0:
        if not isinstance(parsed, dict):
            errors.append(f"{name}: rc==0 but no parsed BENCH line")
        else:
            for key in REQUIRED_PARSED_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: parsed BENCH line missing {key!r}"
                    )
            report = parsed.get("report")
            if report is not None and (
                not isinstance(report, dict)
                or "schema_version" not in report
            ):
                errors.append(
                    f"{name}: embedded report lacks schema_version"
                )
            elif (
                isinstance(report, dict)
                and isinstance(report.get("schema_version"), int)
                and report["schema_version"] >= 5
                and "perf" not in report
            ):
                errors.append(
                    f"{name}: schema-v5 report carries no perf section"
                )
    return errors


def _row(path: str, entry: dict) -> Dict[str, Any]:
    parsed = entry.get("parsed") or {}
    report = parsed.get("report") or {}
    compile_totals = report.get("compile", {}).get("totals", {})
    # v4 reports from a serving run carry the bounded-cache hit rate —
    # the first-class serving metric alongside cut/seconds (rounds
    # without a serving section show "-")
    serving = report.get("serving") or {}
    cache_hit = (serving.get("cache") or {}).get("hit_rate")
    # v5 reports carry the perf observatory's headline columns: overall
    # achieved-vs-peak HBM utilization, overall padding waste, and (for
    # serve-mode rounds) the caller-observed p95 latency
    perf_totals = (report.get("perf") or {}).get("totals") or {}
    p95_ms = (
        ((serving.get("latency") or {}).get("phases") or {})
        .get("total", {}).get("p95_ms")
    )
    # per-kernel seconds (round-9 bench.py `kernel_seconds`); older
    # rounds fall back to the embedded report's scope tree
    kernels = parsed.get("kernel_seconds") or {}
    if not kernels:
        coars = (
            (report.get("scope_tree") or {})
            .get("partitioning", {}).get("children", {})
            .get("coarsening", {}).get("children", {})
        )
        kernels = {
            short: coars[scope]["elapsed_s"]
            for short, scope in (("lp", "lp-clustering"),
                                 ("contraction", "contraction"))
            if scope in coars
        }
    engines = parsed.get("rating_engines") or (
        (report.get("rating") or {}).get("engines") or {}
    )
    # round-11 quality attribution: promoted BENCH keys first, falling
    # back to the embedded report's quality totals for older rounds
    q_totals = (report.get("quality") or {}).get("totals") or {}
    locked = parsed.get(
        "coarsening_locked_frac", q_totals.get("coarsening_locked_frac")
    )
    left = parsed.get(
        "refinement_left_frac", q_totals.get("refinement_left_frac")
    )
    # round-13 out-of-core streaming: promoted BENCH keys first, the
    # embedded report's external section as the older-round fallback
    ext_section = report.get("external") or {}
    ext_s = parsed.get("external_seconds")
    overlap = parsed.get(
        "stream_overlap", ext_section.get("overlap_frac")
    )
    return {
        "round": os.path.basename(path),
        "rc": entry.get("rc"),
        "cut": parsed.get("value"),
        "vs_baseline": parsed.get("vs_baseline"),
        "total_s": parsed.get("total_seconds"),
        "coarsening_s": parsed.get("lp_coarsening_seconds"),
        "lp_s": kernels.get("lp"),
        "contract_s": kernels.get("contraction"),
        "engines": ",".join(
            f"{k}:{v}" for k, v in sorted(engines.items())
        ) or None,
        "platform": parsed.get("platform"),
        "compile_s": compile_totals.get("compile_s"),
        "cache_hit": cache_hit,
        "hbm_util": parsed.get("hbm_util", perf_totals.get("hbm_util")),
        "pad_waste": parsed.get(
            "pad_waste", perf_totals.get("pad_waste")
        ),
        "locked": locked,
        "left": left,
        "external_s": ext_s,
        "overlap": overlap,
        "p95_ms": p95_ms,
        "sup_p95": parsed.get("supervised_p95_ms"),
        # round-16 fleet observatory: promoted throughput keys first,
        # the embedded report's serving.throughput as the fallback
        "rps": parsed.get(
            "requests_per_second",
            (serving.get("throughput") or {}).get("requests_per_second"),
        ),
        "occupancy": parsed.get(
            "batch_occupancy",
            (serving.get("throughput") or {}).get("batch_occupancy"),
        ),
        "dyn_speedup": parsed.get("dynamic_warm_speedup"),
        "dyn_drift": parsed.get("dynamic_cut_drift"),
        # round-19 execution ledger (advisory columns): whether the
        # hbm_util figure is launch-joined truth, and the total
        # host<->device bytes (promoted key first, embedded report's
        # ledger totals as the fallback)
        "honest": parsed.get("util_honest"),
        "xfer_b": _transfer_bytes(parsed, report),
        # round-20 integrity sentinels (advisory column): host-side
        # sentinel wall as % of the partition wall — the dormancy
        # budget as a trend line
        "integ_pct": parsed.get("integrity_overhead_pct"),
        "schema": report.get("schema_version"),
    }


def _transfer_bytes(parsed: dict, report: dict) -> Optional[int]:
    totals = (
        ((report.get("ledger") or {}).get("transfers") or {})
        .get("totals") or {}
    )
    if totals:
        return (
            int(totals.get("h2d_bytes", 0)) + int(totals.get("d2h_bytes", 0))
        ) or None
    phases = parsed.get("transfer_bytes_per_phase")
    if isinstance(phases, dict):
        return sum(
            int(t.get("h2d_bytes", 0)) + int(t.get("d2h_bytes", 0))
            for t in phases.values() if isinstance(t, dict)
        ) or None
    return None


def _fmt(v: Optional[Any]) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


def render(rows: List[Dict[str, Any]]) -> str:
    cols = ("round", "rc", "cut", "vs_baseline", "total_s",
            "coarsening_s", "lp_s", "contract_s", "engines",
            "compile_s", "cache_hit", "hbm_util",
            "pad_waste", "locked", "left", "external_s", "overlap",
            "p95_ms", "sup_p95", "rps", "occupancy",
            "dyn_speedup", "dyn_drift", "honest", "xfer_b",
            "integ_pct", "platform", "schema")
    table = [cols] + [tuple(_fmt(r[c]) for c in cols) for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = [
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row))
        for row in table
    ]
    # movement annotations between consecutive parsed rounds
    prev = None
    for r in rows:
        if prev and r["cut"] and prev["cut"]:
            delta = 100.0 * (r["cut"] - prev["cut"]) / prev["cut"]
            if abs(delta) >= 5.0:
                lines.append(
                    f"note: {prev['round']} -> {r['round']} cut moved "
                    f"{delta:+.1f}%"
                )
        if prev:
            # perf-observatory movement notes (printed, never gated —
            # see the module docstring's gating rationale)
            for col, floor in (("hbm_util", 0.01), ("pad_waste", 0.05),
                               ("locked", 0.1), ("left", 0.1),
                               ("p95_ms", None)):
                a, b = prev.get(col), r.get(col)
                if a is None or b is None:
                    continue
                if col == "p95_ms":
                    if a > 0 and abs(b - a) / a >= 0.5:
                        lines.append(
                            f"note: {prev['round']} -> {r['round']} "
                            f"p95_ms moved {a} -> {b}"
                        )
                elif abs(b - a) >= floor:
                    lines.append(
                        f"note: {prev['round']} -> {r['round']} "
                        f"{col} moved {a} -> {b}"
                    )
        if r["cut"] is not None:
            prev = r
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render / validate the BENCH_r*.json trajectory"
    )
    ap.add_argument(
        "--dir",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="repo root holding BENCH_r*.json (default: this repo)",
    )
    ap.add_argument("--json", action="store_true", help="emit rows as JSON")
    ap.add_argument(
        "--check", action="store_true",
        help="CI mode: exit non-zero on structurally malformed rounds "
        "or a latest round past the kernel/cut gates",
    )
    ap.add_argument(
        "--cut-floor", type=float, default=0.9,
        help="latest round must keep vs_baseline >= this "
        "(platform-independent; default 0.9)",
    )
    ap.add_argument(
        "--coarsening-ceiling", type=float, default=2.0,
        help="latest ACCELERATOR round must keep lp_coarsening_seconds "
        "<= this (default 2.0 s; CPU-fallback rounds skip wall gates)",
    )
    ap.add_argument(
        "--hbm-util-floor", type=float, default=0.005,
        help="latest ACCELERATOR round must keep hbm_util >= this when "
        "the column is present (default 0.005)",
    )
    ap.add_argument(
        "--locked-frac-ceiling", type=float, default=0.75,
        metavar="FRAC",
        help="ADVISORY ceiling on the latest round's "
        "coarsening_locked_frac: past it a note is printed (never a "
        "violation — the attribution floor is relative to each run's "
        "own final partition, a lower bound like hbm_util); default "
        "0.75",
    )
    args = ap.parse_args(argv)

    try:
        rounds = load_rounds(args.dir)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not rounds:
        print(f"no BENCH_r*.json under {args.dir}", file=sys.stderr)
        return 0 if not args.check else 1

    errors: List[str] = []
    # MULTICHIP dist-resilience coverage (rounds >= r06): presence
    # gated on successful rounds; earlier rounds predate the contract
    try:
        for path, entry in load_multichip_rounds(args.dir):
            errors.extend(check_multichip_round(path, entry))
    except (OSError, json.JSONDecodeError) as e:
        errors.append(f"MULTICHIP rounds unreadable: {e}")
    for path, entry in rounds:
        errors.extend(check_round(path, entry))
        # 10M-coverage contract for rounds newer than r05 (see
        # LARGE_COVERAGE_KEYS): presence gated, null tolerated
        name = os.path.basename(path)
        parsed = entry.get("parsed") if isinstance(entry, dict) else None
        rno = _round_number(name)
        if (
            isinstance(parsed, dict)
            and rno is not None and rno >= LARGE_COVERAGE_SINCE
        ):
            for key in LARGE_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: 10M coverage key {key!r} missing "
                        "(r05 regression class — bench.py must emit it "
                        "every run)"
                    )
            for key in QUALITY_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: quality coverage key {key!r} missing "
                        "(bench.py must emit it every run; null marks a "
                        "run without attribution)"
                    )
            for key in EXTERNAL_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: external coverage key {key!r} missing "
                        "(bench.py must emit it every run; null marks a "
                        "skipped/failed external measurement)"
                    )
            for key in SUPERVISED_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: supervised coverage key {key!r} "
                        "missing (bench.py must emit it every run; null "
                        "marks a skipped/failed supervised batch)"
                    )
            for key in DYNAMIC_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: dynamic coverage key {key!r} missing "
                        "(bench.py must emit it every run; null marks a "
                        "skipped/failed dynamic chain measurement)"
                    )
            for key in THROUGHPUT_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: throughput coverage key {key!r} "
                        "missing (bench.py must emit it every run; null "
                        "marks a skipped/failed supervised batch)"
                    )
            for key in LINT_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: lint coverage key {key!r} missing "
                        "(bench.py must emit it every run; null marks "
                        "an errored lint pass)"
                    )
            for key in LEDGER_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: ledger coverage key {key!r} missing "
                        "(bench.py must emit it every run; null marks "
                        "a report without a ledger section)"
                    )
            for key in INTEGRITY_COVERAGE_KEYS:
                if key not in parsed:
                    errors.append(
                        f"{name}: integrity coverage key {key!r} "
                        "missing (bench.py must emit it every run; 0.0 "
                        "marks a kill-switched integrity layer)"
                    )
            errors.extend(_roofline_honesty_errors(name, parsed))
    # kernel/cut regression gate on the LATEST parsed round (--check):
    # older rounds ran older code and are history, not a gate target
    latest = None
    for path, entry in reversed(rounds):
        if isinstance(entry, dict) and isinstance(entry.get("parsed"), dict):
            latest = (os.path.basename(path), entry["parsed"])
            break
    if latest is not None:
        name, parsed = latest
        # advisory quality-attribution note (never gated): a round whose
        # gap mass is mostly locked by coarsening says the next quality
        # PR should aim at clustering, not refinement schedules
        locked_frac = parsed.get("coarsening_locked_frac")
        if (
            isinstance(locked_frac, (int, float))
            and locked_frac > args.locked_frac_ceiling
        ):
            print(
                f"advisory: {name} coarsening_locked_frac {locked_frac} "
                f"exceeds {args.locked_frac_ceiling} — most of the cut "
                "gap is locked in by coarsening; triage with "
                "python -m kaminpar_tpu.telemetry.quality (not gated)"
            )
        vs = parsed.get("vs_baseline")
        if isinstance(vs, (int, float)) and vs > 0 and vs < args.cut_floor:
            errors.append(
                f"{name}: vs_baseline {vs} under the cut floor "
                f"{args.cut_floor}"
            )
        if parsed.get("platform") in ACCEL_PLATFORMS:
            wall = parsed.get("lp_coarsening_seconds")
            if (
                isinstance(wall, (int, float))
                and wall > args.coarsening_ceiling
            ):
                errors.append(
                    f"{name}: lp_coarsening_seconds {wall} over the "
                    f"ceiling {args.coarsening_ceiling}"
                )
            hbm = parsed.get("hbm_util")
            if isinstance(hbm, (int, float)) and hbm < args.hbm_util_floor:
                errors.append(
                    f"{name}: hbm_util {hbm} under the floor "
                    f"{args.hbm_util_floor}"
                )
        elif args.check:
            print(
                f"kernel gate: {name} ran on "
                f"platform={parsed.get('platform')!r} — wall/util gates "
                "skipped (CPU-fallback walls are not TPU numbers); cut "
                "and coverage gates still applied"
            )
    rows = [_row(p, e) for p, e in rounds if isinstance(e, dict)]
    if args.json:
        print(json.dumps(rows))
    else:
        print(render(rows))
    if errors:
        for e in errors:
            print(f"TREND VIOLATION {e}", file=sys.stderr)
    if args.check:
        print(f"trend check: {len(rounds)} round(s), "
              f"{len(errors)} violation(s)")
        return 1 if errors else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
