#!/usr/bin/env bash
# Rebuild the native layer (codec.cpp/codec2.cpp/ip.cpp/fm.cpp and the
# C-ABI shim ckaminpar.cpp) with ASan/UBSan and run the C-API and FM
# tests under it.
#
# The sanitized .so's are dlopen'd into an UNsanitized python, so
# libasan must be LD_PRELOADed into the whole process tree (including
# the compiled C driver test_capi spawns).  Leak detection is off —
# CPython/jax hold allocations for the process lifetime by design; the
# run hunts heap-buffer-overflow / use-after-free / UB, which abort.
#
# Usage:  scripts/run_native_sanitized.sh [extra pytest args]
#         KMP_SANITIZE=address scripts/run_native_sanitized.sh   # ASan only
set -euo pipefail
cd "$(dirname "$0")/.."

export KMP_SANITIZE="${KMP_SANITIZE:-address,undefined}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

LIBASAN="$(gcc -print-file-name=libasan.so)"
if [ ! -e "$LIBASAN" ]; then
    echo "run_native_sanitized: libasan.so not found (gcc too old?)" >&2
    exit 2
fi
# libstdc++ must ride along: python links no C++ runtime, so ASan's
# __cxa_throw interceptor finds no real symbol at init and CHECK-aborts
# on the first C++ exception (jaxlib's MLIR throws StopIteration from
# C++ during every jit compile) without it
LIBSTDCPP="$(g++ -print-file-name=libstdc++.so.6)"
export LD_PRELOAD="$LIBASAN $LIBSTDCPP"
export ASAN_OPTIONS="${ASAN_OPTIONS:-detect_leaks=0,abort_on_error=1}"
export UBSAN_OPTIONS="${UBSAN_OPTIONS:-print_stacktrace=1,halt_on_error=1}"

echo "== sanitized rebuild (KMP_SANITIZE=$KMP_SANITIZE) =="
python - <<'PY'
from kaminpar_tpu import native

flags = native.sanitize_flags()
assert flags, "KMP_SANITIZE unset?"
lib = native.get_lib()
assert lib is not None, "sanitized native build failed (see g++ stderr)"
print(f"sanitized libkmpnative OK ({' '.join(flags)})")
PY

echo "== C-API + native FM tests under ASan/UBSan =="
python -m pytest tests/test_capi.py tests/test_refinement.py \
    -q -p no:cacheprovider "$@"
