#!/usr/bin/env python
"""Utilization probe for the irregular ops that dominate the pipeline.

Round-3's verdict: the "structural per-slot floor" argument was asserted
from one number (9.6 ns/slot gather).  This script measures what fraction
of HBM peak each primitive actually achieves and probes the design space
around the floor:

  * scalar gather m-from-n          (the LP/Jet hot op: labels[dst])
  * row gathers (n, r) tables, r in {2, 4, 8, 16, 128}
    -> if cost is per-INDEX, packing more payload per index is free and
       kernels should gather wider rows instead of more arrays
  * scatter-add, scalar vs wide rows (the conn-table delta op)
  * one-hot matmul rating vs segment_sum (MXU vs scatter for (n, k))
  * dtype sensitivity (int8/int16/int32 gathers)
  * table-size sensitivity (VMEM-resident vs HBM tables)

Achieved bandwidth counts useful bytes only: payload read + payload
written + 4B per index read.  HBM peak for v5e-1 is ~819 GB/s.

Usage: python scripts/microbench_gather.py [log2_m] [log2_n]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

import jax.numpy as jnp
import numpy as np

LOG_M = int(sys.argv[1]) if len(sys.argv) > 1 else 24
LOG_N = int(sys.argv[2]) if len(sys.argv) > 2 else 20
M = 1 << LOG_M
N = 1 << LOG_N
REPS = 4
HBM_PEAK_GBS = 819.0  # v5e single core


def timeit(name, fn, useful_bytes, *args):
    fn_j = jax.jit(fn)
    out = fn_j(*args)  # compile
    int(jnp.sum(jax.tree_util.tree_leaves(out)[0].reshape(-1)[:1]))
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn_j(*args)
        int(jnp.sum(jax.tree_util.tree_leaves(out)[0].reshape(-1)[:1]))
        best = min(best, time.perf_counter() - t0)
    gbs = useful_bytes / best / 1e9
    print(
        json.dumps(
            {
                "op": name,
                "ms": round(best * 1e3, 1),
                "ns_per_index": round(best * 1e9 / M, 2),
                "GB_s": round(gbs, 2),
                "pct_hbm_peak": round(100.0 * gbs / HBM_PEAK_GBS, 2),
            }
        ),
        flush=True,
    )
    return best


def main():
    rng = np.random.RandomState(0)
    dst = jnp.asarray(rng.randint(0, N, M).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, N, N).astype(np.int32))
    print(f"== M=2^{LOG_M} ({M}), N=2^{LOG_N} ({N}) ==", flush=True)

    # --- scalar gather baseline -----------------------------------------
    timeit("gather_scalar_i32", lambda l, d: l[d], M * 12, labels, dst)

    # --- row gathers: same index count, wider payload -------------------
    for r in (2, 4, 8, 16, 32):
        tab = jnp.asarray(
            rng.randint(0, 100, (N, r)).astype(np.int32)
        )
        timeit(
            f"gather_rows_r{r}_i32",
            lambda t, d: t[d],
            M * (4 + 8 * r),
            tab,
            dst,
        )

    # --- dtype sensitivity ----------------------------------------------
    lab16 = labels.astype(jnp.int16)
    lab8 = labels.astype(jnp.int8)
    timeit("gather_scalar_i16", lambda l, d: l[d], M * 8, lab16, dst)
    timeit("gather_scalar_i8", lambda l, d: l[d], M * 6, lab8, dst)

    # --- small-table gather (table fits VMEM) ---------------------------
    for log_small in (10, 14):
        ns = 1 << log_small
        small = jnp.asarray(rng.randint(0, 100, ns).astype(np.int32))
        dsts = jnp.asarray(rng.randint(0, ns, M).astype(np.int32))
        timeit(
            f"gather_scalar_from_2^{log_small}",
            lambda l, d: l[d],
            M * 12,
            small,
            dsts,
        )

    # --- one-hot matmul instead of gather, small table ------------------
    # labels[dst] for a SMALL label table (n <= 2^14) as
    # one_hot(dst) @ labels — MXU does the "gather"
    ns = 1 << 12
    small = jnp.asarray(rng.randint(0, 100, ns).astype(np.int32))
    dsts = jnp.asarray(rng.randint(0, ns, M).astype(np.int32))

    def onehot_gather(l, d):
        oh = jax.nn.one_hot(d, ns, dtype=jnp.bfloat16)
        return (oh @ l.astype(jnp.bfloat16)).astype(jnp.int32)

    timeit("gather_onehot_mxu_2^12", onehot_gather, M * 12, small, dsts)

    # --- scatter-add: scalar vs wide rows -------------------------------
    vals = jnp.asarray(rng.randint(0, 100, M).astype(np.int32))
    timeit(
        "scatter_add_scalar",
        lambda v, d: jnp.zeros(N, jnp.int32).at[d].add(v),
        M * 12 + N * 8,
        vals,
        dst,
    )
    for r in (2, 8):
        valr = jnp.asarray(rng.randint(0, 100, (M, r)).astype(np.int32))
        timeit(
            f"scatter_add_rows_r{r}",
            lambda v, d: jnp.zeros((N, r), jnp.int32).at[d].add(v),
            M * (4 + 8 * r) + N * r * 8,
            valr,
            dst,
        )

    # --- (n, k) rating build: segment_sum vs one-hot matmul -------------
    k = 16
    src = jnp.asarray(np.sort(rng.randint(0, N, M)).astype(np.int32))
    part = jnp.asarray(rng.randint(0, k, N).astype(np.int32))
    w = jnp.asarray(rng.randint(1, 100, M).astype(np.int32))

    def conn_segsum(src, dst, w, part):
        flat = src * k + part[dst]
        return jax.ops.segment_sum(w, flat, num_segments=N * k)

    timeit("conn_nk16_segment_sum", conn_segsum, M * 24 + N * k * 4,
           src, dst, w, part)

    def conn_onehot(src, dst, w, part):
        # one-hot the k-axis only (k small); still needs the dst gather
        # and an m-to-n segment reduction per k column via segment_sum of
        # w * onehot — expressed as a single segment_sum of (m, k) rows
        oh = jax.nn.one_hot(part[dst], k, dtype=jnp.int32) * w[:, None]
        return jax.ops.segment_sum(oh, src, num_segments=N)

    timeit("conn_nk16_onehot_rows", conn_onehot, M * 24 + N * k * 4,
           src, dst, w, part)


if __name__ == "__main__":
    main()
