#!/usr/bin/env python
"""Measure the reference KaMinPar's coarsening wall-clock on the bench graph.

Run once per benchmark-host to produce BASELINE_CPU.json, which bench.py
uses as the vs_baseline denominator.  Usage:

    python scripts/measure_cpu_baseline.py [path-to-reference-KaMinPar-binary]

The binary is built from /root/reference (cmake -DCMAKE_BUILD_TYPE=Release
-DBUILD_TESTING=OFF -DKAMINPAR_BUILD_WITH_SPARSEHASH=OFF
-DKAMINPAR_BUILD_WITH_KASSERT=OFF; target KaMinParApp).  The script writes
the bench RMAT graph in METIS format, runs the binary with the bench's
k/epsilon, parses the coarsening timer from its output, and records the
result with provenance (host core count).
"""

from __future__ import annotations

import json
import multiprocessing
import os
import re
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402


def main() -> None:
    binary = sys.argv[1] if len(sys.argv) > 1 else "/tmp/kmp_build/apps/KaMinPar"
    if not os.path.exists(binary):
        raise SystemExit(f"reference binary not found: {binary}")

    from kaminpar_tpu.io import write_metis

    host = bench.build_graph()
    with tempfile.TemporaryDirectory() as tmp:
        graph_path = os.path.join(tmp, "bench_rmat.metis")
        write_metis(host, graph_path)

        best = float("inf")
        best_cut = None
        for seed in range(2):
            out = subprocess.run(
                [
                    binary,
                    graph_path,
                    "-k",
                    str(bench.BENCH_K),
                    "-e",
                    str(bench.BENCH_EPS),
                    "-s",
                    str(seed),
                ],
                capture_output=True,
                text=True,
                check=True,
            ).stdout
            m = re.search(r"Coarsening:\s*\.*\s*\(?([0-9.]+)\s*s", out)
            if m is None:
                sys.stderr.write(out)
                raise SystemExit("could not parse coarsening time")
            best = min(best, float(m.group(1)))
            mc = re.search(r"Edge cut:\s*(\d+)", out)
            if mc:
                cut = int(mc.group(1))
                best_cut = cut if best_cut is None else min(best_cut, cut)

    result = {
        "lp_coarsening_s": best,
        "edge_cut": best_cut,
        "graph": f"rmat n={bench.RMAT_N} m={bench.RMAT_M} seed={bench.SEED}",
        "k": bench.BENCH_K,
        "epsilon": bench.BENCH_EPS,
        "binary": "reference KaMinPar (default preset), coarsening subtree",
        "cpu_cores": multiprocessing.cpu_count(),
    }
    path = os.path.join(os.path.dirname(__file__), "..", "BASELINE_CPU.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
