#!/usr/bin/env python
"""Measure the reference KaMinPar binary on the bench graphs.

Produces/updates BASELINE_CPU.json, whose `medium_edge_cut` is the
vs_baseline denominator bench.py reports against.  Usage:

    python scripts/measure_cpu_baseline.py [path-to-reference-KaMinPar-binary]

The binary is built from /root/reference:

    cmake -S /root/reference -B /tmp/kmp_build -G Ninja \
        -DCMAKE_BUILD_TYPE=Release -DBUILD_TESTING=OFF \
        -DKAMINPAR_BUILD_WITH_SPARSEHASH=OFF -DKAMINPAR_BUILD_WITH_KASSERT=OFF
    ninja -C /tmp/kmp_build KaMinParApp

Existing keys in BASELINE_CPU.json are preserved (merge, not overwrite),
so large-graph entries measured separately survive a re-run.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import re
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402


SEEDS = (1, 2)
THREADS = 8
# keys written by the pre-quality-metric era of this script; dropped on
# rewrite so stale provenance does not sit next to the live numbers
LEGACY_KEYS = ("lp_coarsening_s", "edge_cut", "graph", "k", "epsilon", "binary")


def run_binary(binary: str, graph_path: str, k: int, eps: float, seed: int):
    """Returns (edge_cut, coarsening_seconds, partitioning_seconds) parsed
    from the binary's result summary and timer tree."""
    out = subprocess.run(
        [binary, graph_path, "-k", str(k), "-e", str(eps), "-s", str(seed),
         "-t", str(THREADS)],
        capture_output=True,
        text=True,
        check=True,
    ).stdout
    m = re.search(r"Edge cut:\s*(\d+)", out)
    if m is None:
        sys.stderr.write(out)
        raise SystemExit("could not parse edge cut from reference output")
    coarse = re.search(r"\|- Coarsening: \.+ ([0-9.]+) s", out)
    part = re.search(r"\|- Partitioning: \.+ ([0-9.]+) s", out)
    return (
        int(m.group(1)),
        float(coarse.group(1)) if coarse else None,
        float(part.group(1)) if part else None,
    )


def _merge_into_baseline(updates: dict, drop: tuple = ()) -> None:
    """Merge `updates` into BASELINE_CPU.json, removing `drop` keys first
    (merge, not overwrite: keys measured by other runs survive)."""
    path = os.path.join(os.path.dirname(__file__), "..", "BASELINE_CPU.json")
    data = {}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    for key in drop:
        data.pop(key, None)
    data.update(updates)
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def main_large(binary: str) -> None:
    """Measure the reference binary's phase times on the LARGE bench
    graphs (the 10M-edge profile_pipeline graph and the scale-22 graph),
    the scales where the repo's crossover claim lives.  Merge-updates
    BASELINE_CPU.json with large10m_* / large22_* keys."""
    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.io import write_metis

    configs = [
        # (key_prefix, n, m, gen_seed, k) — must match
        # scripts/profile_pipeline.py and the scale-22 entry already in
        # BASELINE_CPU.json respectively
        ("large10m", 1 << 20, 10_000_000, 7, 16),
        ("large22", 1 << 22, 40_000_000, 22, 64),
    ]
    for prefix, n, m, gen_seed, k in configs:
        host = make_rmat(n, m, seed=gen_seed)
        with tempfile.TemporaryDirectory() as tmp:
            graph_path = os.path.join(tmp, f"{prefix}.metis")
            write_metis(host, graph_path)
            del host
            runs = [
                run_binary(binary, graph_path, k, bench.BENCH_EPS, s)
                for s in SEEDS
            ]
        best_cut = min(r[0] for r in runs)
        coarsening_s = min((r[1] for r in runs if r[1] is not None), default=None)
        partitioning_s = min((r[2] for r in runs if r[2] is not None), default=None)
        seeds_str = f"{SEEDS[0]}-{SEEDS[-1]}" if len(SEEDS) > 1 else str(SEEDS[0])
        updates = {
            f"{prefix}_graph": f"rmat n={n} m={m} seed={gen_seed}",
            f"{prefix}_edge_cut_k{k}": best_cut,
            f"{prefix}_note": "reference KaMinPar binary (default preset, "
            f"-t {THREADS} on {multiprocessing.cpu_count()} logical CPUs — "
            "when CPUs < threads the threads time-slice, so a 1-CPU box "
            "measures the ~sequential reference and a real 8-core run "
            "would be FASTER (TPU-vs-CPU ratios computed against these "
            f"times are optimistic); best of seeds {seeds_str}) full "
            f"partition, k={k} eps={bench.BENCH_EPS}",
        }
        if coarsening_s is not None:
            updates[f"{prefix}_coarsening_s"] = coarsening_s
        if partitioning_s is not None:
            updates[f"{prefix}_partitioning_s"] = partitioning_s
        _merge_into_baseline(updates)
        print(json.dumps(updates))


def main() -> None:
    args = [a for a in sys.argv[1:] if a != "--large"]
    binary = args[0] if args else "/tmp/kmp_build/apps/KaMinPar"
    if not os.path.exists(binary):
        raise SystemExit(f"reference binary not found: {binary}")
    if "--large" in sys.argv[1:]:
        main_large(binary)
        return

    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.io import write_metis

    host = make_rmat(bench.MED_N, bench.MED_M, seed=bench.MED_SEED)
    with tempfile.TemporaryDirectory() as tmp:
        graph_path = os.path.join(tmp, "bench_rmat.metis")
        write_metis(host, graph_path)
        runs = [
            run_binary(binary, graph_path, bench.BENCH_K, bench.BENCH_EPS, s)
            for s in SEEDS
        ]
        best_cut = min(r[0] for r in runs)
        # phase-time denominators for the bench speed metric: the binary's
        # fastest run (steady-state, same methodology as the TPU side)
        coarsening_s = min((r[1] for r in runs if r[1] is not None), default=None)
        partitioning_s = min((r[2] for r in runs if r[2] is not None), default=None)

    seeds_str = f"{SEEDS[0]}-{SEEDS[-1]}" if len(SEEDS) > 1 else str(SEEDS[0])
    updates = {
        "medium_graph": f"rmat n={bench.MED_N} m={bench.MED_M} "
        f"seed={bench.MED_SEED}",
        "medium_edge_cut": best_cut,
        "medium_note": "reference KaMinPar binary (default preset, "
        f"-t {THREADS}, best of seeds {seeds_str}) full partition on "
        f"the medium bench graph, k={bench.BENCH_K} "
        f"eps={bench.BENCH_EPS}",
        "cpu_cores": multiprocessing.cpu_count(),
    }
    # never pair a fresh cut with stale phase times: when the timer tree
    # failed to parse, drop the old denominators instead of keeping them
    drop = list(LEGACY_KEYS)
    if coarsening_s is not None:
        updates["medium_coarsening_s"] = coarsening_s
    else:
        drop.append("medium_coarsening_s")
    if partitioning_s is not None:
        updates["medium_partitioning_s"] = partitioning_s
    else:
        drop.append("medium_partitioning_s")
    _merge_into_baseline(updates, drop=tuple(drop))
    print(json.dumps({"medium_edge_cut": best_cut}))


if __name__ == "__main__":
    main()
