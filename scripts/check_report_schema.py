#!/usr/bin/env python
"""Validate a run-report JSON against the checked-in schema.

CI / tooling backstop for the telemetry run report (`--report-json`,
bench.py's embedded `report`): the schema lives at
kaminpar_tpu/telemetry/run_report.schema.json and this validator is a
dependency-free subset of JSON Schema (type / required / properties /
items / enum) — enough to catch drift (renamed or dropped sections,
type changes) without pulling in the `jsonschema` package.  A fast
tier-1 test (tests/test_telemetry.py) generates a report and runs this
validator, so schema and producer cannot drift apart silently.

Usage:  python scripts/check_report_schema.py report.json [--schema S]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, List

DEFAULT_SCHEMA = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    os.pardir,
    "kaminpar_tpu",
    "telemetry",
    "run_report.schema.json",
)

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "number": (int, float),
    "integer": int,
    "boolean": bool,
    "null": type(None),
}


def _type_ok(value: Any, expected) -> bool:
    if isinstance(expected, list):  # union, e.g. ["number", "null"]
        return any(_type_ok(value, e) for e in expected)
    py = _TYPES.get(expected)
    if py is None:
        return True  # unknown type keyword: don't fail on it
    if expected in ("integer", "number") and isinstance(value, bool):
        return False  # bool is an int subclass in Python; JSON disagrees
    return isinstance(value, py)


def validate_instance(instance: Any, schema: dict, path: str = "$") -> List[str]:
    """Returns a list of human-readable violations (empty = valid)."""
    errors: List[str] = []
    expected = schema.get("type")
    if expected is not None and not _type_ok(instance, expected):
        errors.append(
            f"{path}: expected {expected}, got {type(instance).__name__}"
        )
        return errors  # child checks would only cascade
    enum = schema.get("enum")
    if enum is not None and instance not in enum:
        errors.append(f"{path}: value {instance!r} not in enum {enum}")
    if isinstance(instance, dict):
        for req in schema.get("required", []):
            if req not in instance:
                errors.append(f"{path}: missing required key {req!r}")
        props = schema.get("properties", {})
        for key, sub in props.items():
            if key in instance:
                errors.extend(
                    validate_instance(instance[key], sub, f"{path}.{key}")
                )
    if isinstance(instance, list):
        items = schema.get("items")
        if items:
            for i, item in enumerate(instance):
                errors.extend(
                    validate_instance(item, items, f"{path}[{i}]")
                )
    return errors


def version_checks(report: Any) -> List[str]:
    """Schema_version-conditional requirements the dependency-free
    validator subset cannot express (no if/then): v2+ reports must carry
    the `progress` and `compile` sections, v3+ additionally the
    `checkpoint` and `anytime` sections, v4+ additionally the `serving`
    section, v5+ additionally the `perf` section, v6+ additionally the
    `memory_budget` section, v7+ additionally the `quality` section,
    v8+ additionally the `dist_resilience` section, v9+ additionally
    the `external` section, v10+ additionally the `supervision`
    section, v11+ additionally the `dynamic` section, v12+ additionally
    the `tracing` section, v13+ additionally the `ledger` section,
    v14+ additionally the `integrity` section; older reports remain
    valid without them during the transition."""
    errors: List[str] = []
    if not isinstance(report, dict):
        return errors
    version = report.get("schema_version")
    if not isinstance(version, int):
        return errors
    required_by_version = [
        (2, ("progress", "compile")),
        (3, ("checkpoint", "anytime")),
        (4, ("serving",)),
        (5, ("perf",)),
        (6, ("memory_budget",)),
        (7, ("quality",)),
        (8, ("dist_resilience",)),
        (9, ("external",)),
        (10, ("supervision",)),
        (11, ("dynamic",)),
        (12, ("tracing",)),
        (13, ("ledger",)),
        (14, ("integrity",)),
    ]
    for min_version, keys in required_by_version:
        if version < min_version:
            continue
        for key in keys:
            if key not in report:
                errors.append(
                    f"$: schema_version {version} requires section {key!r}"
                )
    return errors


def _minimal_v1_report() -> dict:
    """A minimal schema_version-1 report (the pre-progress/compile
    layout) — the transition fixture --selftest validates alongside the
    live v2 producer, so v1 artifacts (old BENCH lines, archived
    --report-json files) keep validating."""
    return {
        "schema_version": 1,
        "environment": {
            "version": "0", "python": "3", "platform": "cpu",
            "device_count": 1, "process_count": 1, "jax_version": "0",
        },
        "run": {"preset": "default", "seed": 1, "k": 2},
        "result": {"cut": 0, "imbalance": 0.0, "feasible": True},
        "scope_tree": {},
        "levels": [],
        "comm": {"caveat": "none", "records": []},
        "events": [],
        "counters": {},
        "lane_gather": {"mode": "not-probed"},
        "faults": {"plan": None, "sites": [], "injected": []},
        "degraded": [],
        "output_gate": {"checked": False},
    }


def _minimal_v2_report() -> dict:
    """A minimal schema_version-2 report (progress/compile present, no
    checkpoint/anytime sections) — the second transition fixture."""
    r = _minimal_v1_report()
    r["schema_version"] = 2
    r["progress"] = []
    r["compile"] = {"caveat": "none", "totals": {}, "phases": {}}
    return r


def _minimal_v3_report() -> dict:
    """A minimal schema_version-3 report (checkpoint/anytime present, no
    serving section) — the third transition fixture."""
    r = _minimal_v2_report()
    r["schema_version"] = 3
    r["checkpoint"] = {"enabled": False}
    r["anytime"] = {"anytime": False}
    return r


def _minimal_v4_report() -> dict:
    """A minimal schema_version-4 report (serving present, no perf
    section) — the fourth transition fixture."""
    r = _minimal_v3_report()
    r["schema_version"] = 4
    r["serving"] = {"enabled": False}
    return r


def _minimal_v5_report() -> dict:
    """A minimal schema_version-5 report (perf present, no
    memory_budget section) — the fifth transition fixture."""
    r = _minimal_v4_report()
    r["schema_version"] = 5
    r["perf"] = {"enabled": False}
    return r


def _minimal_v6_report() -> dict:
    """A minimal schema_version-6 report (memory_budget present, no
    quality section) — the sixth transition fixture."""
    r = _minimal_v5_report()
    r["schema_version"] = 6
    r["memory_budget"] = {"enabled": False}
    return r


def _minimal_v7_report() -> dict:
    """A minimal schema_version-7 report (quality present, no
    dist_resilience section) — the seventh transition fixture."""
    r = _minimal_v6_report()
    r["schema_version"] = 7
    r["quality"] = {"enabled": False}
    return r


def _minimal_v8_report() -> dict:
    """A minimal schema_version-8 report (dist_resilience present, no
    external section) — the eighth transition fixture."""
    r = _minimal_v7_report()
    r["schema_version"] = 8
    r["dist_resilience"] = {"enabled": False}
    return r


def _minimal_v9_report() -> dict:
    """A minimal schema_version-9 report (external present, no
    supervision section) — the ninth transition fixture."""
    r = _minimal_v8_report()
    r["schema_version"] = 9
    r["external"] = {"enabled": False}
    return r


def _minimal_v10_report() -> dict:
    """A minimal schema_version-10 report (supervision present, no
    dynamic section) — the tenth transition fixture."""
    r = _minimal_v9_report()
    r["schema_version"] = 10
    r["supervision"] = {"enabled": False}
    return r


def _minimal_v11_report() -> dict:
    """A minimal schema_version-11 report (dynamic present, no
    tracing section) — the eleventh transition fixture."""
    r = _minimal_v10_report()
    r["schema_version"] = 11
    r["dynamic"] = {"enabled": False}
    return r


def _minimal_v12_report() -> dict:
    """A minimal schema_version-12 report (tracing present, no
    ledger section) — the twelfth transition fixture."""
    r = _minimal_v11_report()
    r["schema_version"] = 12
    r["tracing"] = {"enabled": False, "traces": []}
    return r


def _minimal_v13_report() -> dict:
    """A minimal schema_version-13 report (ledger present, no
    integrity section) — the thirteenth transition fixture."""
    r = _minimal_v12_report()
    r["schema_version"] = 13
    r["ledger"] = {"enabled": False}
    return r


def _selftest_report(path: str) -> None:
    """Generate a minimal live report so producer and schema are checked
    against each other with no partition run (the pre-commit /
    check_all.sh fast path).  Annotates non-default `checkpoint`,
    `anytime`, and `serving` sections so the v3/v4 producer surface is
    exercised, not just its empty defaults; the v5 `perf` section comes
    from the live observatory (a pad-waste record and a memory sample
    are injected so the producer emits non-empty subsections)."""
    # run as a script, sys.path[0] is scripts/ — add the repo root
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from kaminpar_tpu import telemetry
    from kaminpar_tpu.telemetry.report import write_run_report

    telemetry.enable()
    telemetry.annotate(result={"cut": 0, "imbalance": 0.0, "feasible": True})
    telemetry.annotate(
        checkpoint={
            "enabled": True, "dir": "/tmp/ckpt", "memory_only": False,
            "generation": 2, "writes": 2, "bytes": 1024, "wall_s": 0.01,
            "resumed_from": "uncoarsen:1",
            "snapshots": ["level-0", "state"],
        },
        anytime={
            "anytime": True, "reason": "budget", "stage": "uncoarsen:1",
            "budget_s": 1.0, "grace_s": 30.0, "elapsed_s": 1.2,
        },
        memory_budget={
            "enabled": True, "budget_bytes": 1 << 30,
            "estimate_bytes": 900 << 20, "bucket": "8192/65536/4",
            "rung": 2, "rung_name": "spill-hierarchy", "initial_rung": 0,
            "exhausted": False, "watermark_bytes": 800 << 20,
            "pressure_events": 1, "shed_cache_bytes": 4096,
            "spills": {"count": 2, "bytes": 1 << 20, "reloads": 2,
                       "reload_bytes": 1 << 20},
        },
        serving={
            "enabled": True,
            "requests": [
                {"request_id": "req-1", "verdict": "served", "k": 4,
                 "n": 100, "m": 400, "cut": 12, "imbalance": 0.01,
                 "feasible": True, "cached": False, "gate_valid": True,
                 "bucket": "256/512/4", "wall_s": 0.5,
                 "hard_ceiling_s": 30.0},
                {"request_id": "req-2", "verdict": "rejected",
                 "reason": "queue-full", "k": 4, "n": -1, "m": -1,
                 "cut": -1, "imbalance": 0.0, "feasible": False,
                 "cached": False, "wall_s": 0.0},
            ],
            "counts": {"served": 1, "anytime": 0, "degraded": 0,
                       "rejected": 1, "failed": 0},
            "admission": {"max_queue_depth": 64,
                          "max_queued_cost": 5e7,
                          "max_request_cost": 2.5e7, "rejected": 1},
            "cache": {"result": {"hits": 0, "misses": 1,
                                 "hit_rate": 0.0},
                      "executable": {"buckets": 1, "hits": 0,
                                     "misses": 1, "hit_rate": 0.0},
                      "hit_rate": 0.0},
            "drained": False,
        },
        dynamic={
            "enabled": True,
            "sessions": [
                {"id": "s1", "n": 100, "m": 400, "k": 4,
                 "deltas_applied": 3, "in_place": 2, "rebuilds": 1,
                 "repartitions": 3, "chain": "dyn:abc123",
                 "bucket": "256/512/4", "cut": 10},
            ],
            "decisions": [
                {"session": "s1", "step": 1, "mode": "warm",
                 "drift": 0.01, "cut_before": 12, "cut": 10,
                 "feasible": True, "stable": True, "gate_valid": True,
                 "escalated": False, "seeded": 1, "in_place": True,
                 "wall_s": 0.2, "warm_wall_s": 0.2,
                 "cold_wall_s": None},
                {"session": "s1", "step": 2, "mode": "replica",
                 "drift": 0.4, "cut_before": 10, "cut": 11,
                 "feasible": True, "stable": True, "escalated": False,
                 "seeded": 0, "wall_s": 0.5, "warm_wall_s": 0.2,
                 "cold_wall_s": 0.3, "replica_cuts": [12, 11]},
            ],
            "counts": {"warm": 1, "cold": 0, "replica": 1,
                       "escalated": 0, "deltas": 3, "in_place": 2,
                       "rebuilds": 1},
            "cut_trajectory": [10, 11],
        },
        supervision={
            "enabled": True,
            "isolation": "process",
            "workers": {"spawned": 2, "recycled": 1, "killed": 1,
                        "crashed": 1, "requests": 10},
            "hangs": [{"stage": "worker-compute",
                       "path": "partitioning.coarsening",
                       "ceiling_s": 2.0, "request": "req-9",
                       "worker_pid": 1234}],
            "heartbeat": {"file": "/tmp/hb", "count": 42},
            "watchdog": {"armed": 3, "fired": 1},
        },
    )
    # exercise the v5 perf producer surface: one pad-waste record and
    # one barrier-style memory sample (both host-side no-ops when the
    # layer is off; here telemetry is on so they land in the report)
    from kaminpar_tpu.telemetry import perf

    perf.record_padding(n=100, n_pad=256, m=400, m_pad=512, k=4, k_pad=4)
    perf.sample_memory("selftest")
    # exercise the v7 quality producer surface: drive the recorder over
    # a tiny handmade hierarchy (pure numpy — no device work) so the
    # section carries a real attribution row, not just its default
    from kaminpar_tpu.graphs.factories import make_cycle
    from kaminpar_tpu.telemetry import quality

    if quality.enabled():
        import numpy as np

        g = make_cycle(8)
        qh = quality.begin("selftest")
        try:
            # one contraction: pair up the cycle's nodes
            quality.note_cmap(
                1, np.repeat(np.arange(4, dtype=np.int64), 2), 8
            )
            part = np.asarray([0, 0, 0, 0, 1, 1, 1, 1], dtype=np.int32)
            quality.note_projected(1, cut=4)
            quality.note_refined(1, cut=3)
            quality.finalize_host(qh, g, part)
        finally:
            quality.end(qh)
    write_run_report(path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a kaminpar-tpu run report against the schema"
    )
    ap.add_argument(
        "report", nargs="?", default=None,
        help="run-report JSON file (--report-json); omit with --selftest",
    )
    ap.add_argument(
        "--schema", default=DEFAULT_SCHEMA, help="schema file to check against"
    )
    ap.add_argument(
        "--selftest", action="store_true",
        help="generate a minimal report from the live producer (schema "
        "v14) and validate it plus the embedded v1-v13 transition "
        "fixtures (no report file needed)",
    )
    args = ap.parse_args(argv)

    if args.selftest:
        if args.report is not None:
            ap.error("--selftest generates its own report; drop the "
                     "report argument (or the flag) — refusing to "
                     "silently ignore the given file")
        import tempfile

        fd, args.report = tempfile.mkstemp(
            prefix="kmp_report_", suffix=".json"
        )
        os.close(fd)
        try:
            _selftest_report(args.report)
            with open(args.schema) as f:
                schema = json.load(f)
            with open(args.report) as f:
                report = json.load(f)
        finally:
            os.unlink(args.report)
        # live producer must emit v14 (progress/compile +
        # checkpoint/anytime + serving + perf + memory_budget +
        # quality + dist_resilience + external + supervision +
        # dynamic + tracing + ledger + integrity)
        if report.get("schema_version") != 14:
            print(
                f"SCHEMA VIOLATION $: selftest producer emitted "
                f"schema_version {report.get('schema_version')!r}, "
                f"expected 14",
                file=sys.stderr,
            )
            return 1
        for key in ("checkpoint", "anytime", "serving", "perf",
                    "memory_budget", "quality", "dist_resilience",
                    "external", "supervision", "dynamic", "tracing",
                    "ledger", "integrity"):
            if key not in report:
                print(
                    f"SCHEMA VIOLATION $: selftest producer emitted no "
                    f"{key!r} section",
                    file=sys.stderr,
                )
                return 1
        # the injected pad-waste record must surface as a non-empty
        # producer subsection (catches a silently dead observatory);
        # KAMINPAR_TPU_PERF=0 legitimately disables the layer
        if report["perf"].get("enabled") and not report["perf"].get(
            "pad_waste"
        ):
            print(
                "SCHEMA VIOLATION $: selftest perf section carries no "
                "pad_waste rows despite an injected record",
                file=sys.stderr,
            )
            return 1
        # the injected hierarchy must surface as a non-default quality
        # section (catches a silently dead quality observatory);
        # KAMINPAR_TPU_QUALITY=0 legitimately disables the layer
        if report["quality"].get("enabled") and not report["quality"].get(
            "levels"
        ):
            print(
                "SCHEMA VIOLATION $: selftest quality section carries "
                "no level rows despite an injected hierarchy",
                file=sys.stderr,
            )
            return 1
        # transition coverage: the v1-v12 layouts must STILL validate
        for label, fixture in (
            ("v1", _minimal_v1_report()), ("v2", _minimal_v2_report()),
            ("v3", _minimal_v3_report()), ("v4", _minimal_v4_report()),
            ("v5", _minimal_v5_report()), ("v6", _minimal_v6_report()),
            ("v7", _minimal_v7_report()), ("v8", _minimal_v8_report()),
            ("v9", _minimal_v9_report()), ("v10", _minimal_v10_report()),
            ("v11", _minimal_v11_report()), ("v12", _minimal_v12_report()),
            ("v13", _minimal_v13_report()),
        ):
            fx_errors = (
                validate_instance(fixture, schema) + version_checks(fixture)
            )
            if fx_errors:
                for e in fx_errors:
                    print(
                        f"SCHEMA VIOLATION ({label} fixture) {e}",
                        file=sys.stderr,
                    )
                return 1
    elif args.report is None:
        ap.error("a report file is required unless --selftest is given")
    else:
        with open(args.schema) as f:
            schema = json.load(f)
        with open(args.report) as f:
            report = json.load(f)

    errors = validate_instance(report, schema) + version_checks(report)
    if errors:
        for e in errors:
            print(f"SCHEMA VIOLATION {e}", file=sys.stderr)
        print(f"{args.report}: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    print(f"{args.report}: OK (schema_version "
          f"{report.get('schema_version')})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
