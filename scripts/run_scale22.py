#!/usr/bin/env python
"""Scale-22 (RMAT n=2^22, ~38.7M undirected edges, k=64) end-to-end run.
Usage: python scripts/run_scale22.py [reps]"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
import numpy as np

reps = int(sys.argv[1]) if len(sys.argv) > 1 else 1

from kaminpar_tpu.graphs.factories import make_rmat
from kaminpar_tpu.graphs.host import host_partition_metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.utils.logger import OutputLevel

host = make_rmat(1 << 22, 40_000_000, seed=22)
print(f"graph: n={host.n} m={host.m}", flush=True)
for rep in range(reps):
    p = KaMinPar("default")
    p.set_output_level(OutputLevel.QUIET)
    t0 = time.perf_counter()
    part = p.set_graph(host).compute_partition(k=64, epsilon=0.03, seed=1)
    dt = time.perf_counter() - t0
    m = host_partition_metrics(host, part, 64)
    print(f"rep{rep}: {dt:.1f}s cut={m['cut']} imb={m['imbalance']:.4f}",
          flush=True)
