#!/bin/bash
# One TPU measurement session, ordered by value-per-minute — run when
# the tunnel grants a chip (after a pool outage, windows may be short).
# Each stage appends to its own log; rerunning skips nothing (cheap
# stages are idempotent and the expensive ones want fresh numbers).
set -x
cd /root/repo

# 1. the decisive probe: dynamic_gather speed on tall tables (~5 min)
python scripts/probe_dynamic_gather.py 2>&1 | tee -a /tmp/tpu_probe.log

# 2. one warm-up + timed 10M LP+coarsening with the routed path
#    (bench's own measure; also records the medium line) (~15-30 min,
#    first run pays routed-path compiles)
python bench.py 2>&1 | tee -a /tmp/tpu_bench1.log

# 3. second bench run: warm-cache steady state (~10 min)
python bench.py 2>&1 | tee -a /tmp/tpu_bench2.log

# 4. configs[3] analog re-record (strong k=32) — VERDICT r4 #5 wants
#    the warm wall under 250 s at equal-or-better cut (~20-40 min)
python scripts/record_configs.py fe_ocean 2>&1 | tee -a /tmp/tpu_cfg3.log
