#!/usr/bin/env python
"""Standalone LP clustering benchmark.

Analog of apps/benchmarks/shm_label_propagation_benchmark.cc: run the LP
clustering kernel alone on a given (or generated) graph and report
wall-clock per call plus clustering statistics.

Usage:
  python benchmarks/lp_benchmark.py <graph.metis|gen:spec> [--engine auto]
      [--iterations 5] [--reps 3] [--max-cluster-weight-frac 0.0625]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("graph")
    p.add_argument("--engine", default="auto",
                   choices=["auto", "sort", "sort2", "hash", "dense"])
    p.add_argument("--iterations", type=int, default=5)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--max-cluster-weight-frac", type=float, default=1 / 16)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import jax.numpy as jnp
    import numpy as np

    from kaminpar_tpu import io as io_mod
    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.graphs.factories import generate
    from kaminpar_tpu.ops.lp import LPConfig, lp_cluster

    if args.graph.startswith("gen:"):
        host = generate(args.graph)
    else:
        host = io_mod.load_graph(args.graph)
    graph = device_graph_from_host(host)
    cfg = LPConfig(rating=args.engine, num_iterations=args.iterations)
    mcw = jnp.int32(
        max(1, int(host.node_weight_array().sum() * args.max_cluster_weight_frac))
    )

    lab = lp_cluster(graph, mcw, jnp.int32(args.seed), cfg)
    int(jnp.sum(lab))  # force completion (compile + run)

    best = float("inf")
    for r in range(args.reps):
        t = time.perf_counter()
        lab = lp_cluster(graph, mcw, jnp.int32(args.seed + 1 + r), cfg)
        int(jnp.sum(lab))
        best = min(best, time.perf_counter() - t)

    lab_np = np.asarray(lab)[: host.n]
    w = np.zeros(graph.n_pad, dtype=np.int64)
    np.add.at(w, lab_np, host.node_weight_array())
    print(json.dumps({
        "n": int(host.n), "m": int(host.m),
        "engine": args.engine,
        "seconds": round(best, 4),
        "num_clusters": int(len(np.unique(lab_np))),
        "max_cluster_weight": int(w.max()),
        "cap": int(mcw),
    }))


if __name__ == "__main__":
    sys.exit(main())
