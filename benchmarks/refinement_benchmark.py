#!/usr/bin/env python
"""Standalone refinement benchmark.

Analog of apps/benchmarks/shm_refinement_benchmark.cc: drive ONE refiner
on a given graph + random (or supplied) partition and report wall-clock
and cut improvement.

Usage:
  python benchmarks/refinement_benchmark.py <graph|gen:spec> -k 16
      --refiner jet|lp|balancer [--reps 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("graph")
    p.add_argument("-k", type=int, default=16)
    p.add_argument("--refiner", default="jet", choices=["jet", "lp", "balancer"])
    p.add_argument("--epsilon", type=float, default=0.03)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import jax.numpy as jnp
    import numpy as np

    from kaminpar_tpu import io as io_mod
    from kaminpar_tpu.context import JetRefinementContext
    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.graphs.factories import generate
    from kaminpar_tpu.ops import metrics
    from kaminpar_tpu.ops.balancer import overload_balance
    from kaminpar_tpu.ops.jet import jet_refine
    from kaminpar_tpu.ops.lp import lp_refine

    if args.graph.startswith("gen:"):
        host = generate(args.graph)
    else:
        host = io_mod.load_graph(args.graph)
    graph = device_graph_from_host(host)
    k = args.k
    rng = np.random.default_rng(args.seed)
    part0 = np.zeros(graph.n_pad, np.int32)
    part0[: host.n] = rng.integers(0, k, host.n)
    part0 = jnp.asarray(part0)
    nw = host.node_weight_array()
    cap = int(np.ceil(nw.sum() / k * (1 + args.epsilon)))
    caps = jnp.full((k,), cap, jnp.int32)

    def run(seed):
        if args.refiner == "jet":
            return jet_refine(graph, part0, k, caps, jnp.int32(seed),
                              JetRefinementContext())
        if args.refiner == "lp":
            return lp_refine(graph, part0, k, caps, jnp.int32(seed))
        return overload_balance(graph, part0, k, caps, jnp.int32(seed))

    cut0 = int(metrics.edge_cut(graph, part0))
    out = run(args.seed)
    int(jnp.sum(out))
    best = float("inf")
    for r in range(args.reps):
        t = time.perf_counter()
        out = run(args.seed + r)
        int(jnp.sum(out))
        best = min(best, time.perf_counter() - t)
    cut1 = int(metrics.edge_cut(graph, out))
    bw = np.zeros(k, np.int64)
    np.add.at(bw, np.asarray(out)[: host.n], nw)
    print(json.dumps({
        "n": int(host.n), "m": int(host.m), "k": k,
        "refiner": args.refiner,
        "seconds": round(best, 4),
        "cut_before": cut0, "cut_after": cut1,
        "max_block_weight": int(bw.max()), "cap": cap,
    }))


if __name__ == "__main__":
    sys.exit(main())
