#!/usr/bin/env python
"""Round benchmark: device LP clustering + contraction wall-clock.

Measures the framework's hot phases (SURVEY.md §3.3: LP iteration +
cluster contraction — HOT LOOP 1 and 2 of the reference's call stack) on a
10M-edge RMAT graph, the BASELINE.md workload class, over two multilevel
coarsening levels.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}
vs_baseline is the CPU reference speedup factor: cpu_seconds / our_seconds,
where cpu_seconds comes from BASELINE_CPU.json (measured once with the
reference KaMinPar binary's coarsening timer on the same graph; see
scripts/measure_cpu_baseline.py).  Target per BASELINE.md: >= 4x.
"""

from __future__ import annotations

import json
import os
import time

RMAT_N = 1 << 20
RMAT_M = 10_000_000
SEED = 42
LEVELS = 2
SHRINK = 64  # max cluster weight = total weight / SHRINK, per level


def build_graph():
    from kaminpar_tpu.graphs.factories import make_rmat

    return make_rmat(RMAT_N, RMAT_M, seed=SEED)


def run_pipeline(graph, seed: int):
    """LEVELS x (LP cluster + contract); returns final coarse n."""
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.ops.contraction import contract_clustering
    from kaminpar_tpu.ops.lp import lp_cluster

    g = graph
    c_n = None
    for level in range(LEVELS):
        total_w = int(jax.device_get(g.total_node_weight()))
        mcw = jnp.int32(max(1, total_w // SHRINK))
        labels = lp_cluster(g, mcw, jnp.int32(seed + level))
        coarse, c_n, _ = contract_clustering(g, labels)
        g = coarse.graph
    jax.block_until_ready(g.node_w)
    return c_n


def main() -> None:
    import jax

    from kaminpar_tpu.graphs.csr import device_graph_from_host

    host = build_graph()
    graph = device_graph_from_host(host)
    jax.block_until_ready(graph.node_w)

    run_pipeline(graph, seed=0)  # warmup: compile every shape bucket

    best = float("inf")
    for rep in range(3):
        t0 = time.perf_counter()
        run_pipeline(graph, seed=rep)
        best = min(best, time.perf_counter() - t0)

    vs = 0.0
    baseline_path = os.path.join(os.path.dirname(__file__), "BASELINE_CPU.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            cpu = json.load(f)
        cpu_s = cpu.get("lp_coarsening_s")
        if cpu_s:
            vs = cpu_s / best

    print(
        json.dumps(
            {
                "metric": "lp_coarsening_wall_rmat10M",
                "value": round(best, 4),
                "unit": "s",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
