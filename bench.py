#!/usr/bin/env python
"""Round benchmark: end-to-end partition quality vs the reference binary.

Partitions the medium bench RMAT graph (n=2^16, m=600k — the BASELINE.md
workload class at a size whose full pipeline fits comfortably in a bench
run) into k=16 at eps=0.03 with the default preset, entirely through the
product path (KaMinPar facade -> device kernels -> host IP), best of two
seeds — the same methodology as the recorded reference number — and
compares the edge cut against the reference KaMinPar binary's cut on the
SAME graph (BASELINE_CPU.json medium_edge_cut, measured with the binary
built from /root/reference; see scripts/measure_cpu_baseline.py).

Prints ONE JSON line:
  {"metric": "edge_cut_rmat600k_k16", "value": <our cut>, "unit": "cut",
   "vs_baseline": <reference_cut / our_cut>}
vs_baseline > 1 means our cut BEATS the reference binary's (the
BASELINE.md north star asks for within 3%, i.e. >= 0.97).  An infeasible
partition reports vs_baseline = 0.

Larger-scale numbers (10M-edge graph: cut 0.47x reference; scale-22
k=64: cut 0.63x reference) are tracked in docs/performance.md.
"""

from __future__ import annotations

import json
import os

#: Quality-attribution keys the BENCH line ALWAYS carries (the same
#: never-vanish contract as the 10M block: null marks a run whose report
#: produced no attribution, ABSENCE is a coverage regression —
#: scripts/bench_trend.py gates presence from r06 on, and check_all.sh
#: asserts this contract without running the full bench).
QUALITY_KEYS = ("coarsening_locked_frac", "refinement_left_frac")

#: Out-of-core streaming keys (round 13, kaminpar_tpu/external/): the
#: wall of a forced-budget `--scheme external` run of the medium bench
#: graph and its upload/compute overlap fraction — same never-vanish
#: contract (null = the measurement failed or was skipped, ABSENCE =
#: silent coverage loss, gated by bench_trend from r06 on).
EXTERNAL_KEYS = ("external_seconds", "stream_overlap")

#: Supervised-serving key (round 14, resilience/supervisor.py): p95 of
#: a small `--serve-isolation process` batch — the latency cost of the
#: hang/crash-containment boundary (spawn amortized over the warm
#: worker).  Same never-vanish contract (null = inproc/skipped/failed,
#: ABSENCE = silent coverage loss, gated by bench_trend from r06 on).
SUPERVISED_KEYS = ("supervised_p95_ms",)

#: Serving-throughput keys (round 16, fleet observatory): sliding-window
#: requests/second and mean padded-executable occupancy of the SAME
#: supervised batch the p95 comes from — same never-vanish contract
#: (null = skipped/failed, ABSENCE = silent coverage loss, gated by
#: bench_trend from r06 on).
THROUGHPUT_KEYS = ("requests_per_second", "batch_occupancy")


def supervised_key(p95_ms=None) -> dict:
    """The BENCH line's supervised-serving key; always present, null
    when the supervised measurement was skipped or failed."""
    return {"supervised_p95_ms": p95_ms}


def throughput_keys(rps=None, occupancy=None) -> dict:
    """The BENCH line's serving-throughput keys; always present, null
    when the supervised measurement was skipped or failed."""
    return {"requests_per_second": rps, "batch_occupancy": occupancy}


def _measure_supervised():
    """(p95_ms, rps, occupancy) of a 3-request supervised batch: compute
    runs in a spawned worker under the hard wall-clock watchdog, so the
    p95 prices the containment boundary (npz exchange + worker
    supervision) against the same graphs served inproc; rps/occupancy
    are the service's own throughput figures for the batch
    (summary()["throughput"], the fleet observatory's live pair)."""
    from kaminpar_tpu.serving import (
        PartitionRequest,
        PartitionService,
        ServiceConfig,
    )

    svc = PartitionService("default", ServiceConfig(
        isolation="process", worker_max_requests=16,
    ))
    try:
        reqs = [
            PartitionRequest(
                f"gen:rgg2d;n=4096;avg_degree=8;seed={i}", k=4, seed=1,
                request_id=f"sup-{i}",
            )
            for i in range(3)
        ]
        recs = svc.serve(reqs)
        bad = [r.verdict for r in recs if r.verdict != "served"]
        assert not bad, f"supervised batch verdicts: {bad}"
        lat = svc.latency_summary()["phases"]["total"]
        throughput = svc.throughput_summary()
        return (
            lat["p95_ms"],
            throughput["requests_per_second"],
            throughput["batch_occupancy"],
        )
    finally:
        svc.close()


#: Dynamic-repartitioning keys (round 15, kaminpar_tpu/dynamic/):
#: warm-vs-cold wall speedup and the max warm-vs-cold-twin cut drift
#: over a short delta chain on the medium bench graph.  Same
#: never-vanish contract (null = skipped/failed, ABSENCE = silent
#: coverage loss, gated by bench_trend from r06 on).
DYNAMIC_KEYS = ("dynamic_warm_speedup", "dynamic_cut_drift")


def dynamic_keys(speedup=None, drift=None) -> dict:
    """The BENCH line's dynamic-repartitioning keys; always present,
    null when the dynamic measurement was skipped or failed."""
    return {"dynamic_warm_speedup": speedup, "dynamic_cut_drift": drift}


def _measure_dynamic():
    """A 4-step ~1% churn delta chain on the medium bench graph: per
    step, a warm-started v-cycle repartition AND its cold twin from
    scratch.  Returns (warm_speedup, cut_drift): mean cold wall / mean
    warm wall, and the max fractional cut gap warm-vs-cold-twin —
    the dynamic acceptance pair (warm must be faster, and within the
    diff gate of the cold run it replaces)."""
    import time

    from kaminpar_tpu.dynamic import GraphSession, synth_chain
    from kaminpar_tpu.dynamic.repartition import repartition
    from kaminpar_tpu.graphs.factories import generate
    from kaminpar_tpu.kaminpar import KaMinPar, context_from_preset

    graph = generate(f"rmat;n={MED_N};m={MED_M};seed={MED_SEED}")
    batches = synth_chain(graph, steps=4, seed=41, edge_churn=0.01)
    ctx = context_from_preset("default")
    session = GraphSession("bench", graph, k=BENCH_K)
    solver = KaMinPar(ctx)
    solver.set_graph(session.graph)
    part = solver.compute_partition(k=BENCH_K, epsilon=BENCH_EPS, seed=1)
    m0 = solver.result_metrics(session.graph, part)
    session.commit_partition(part, int(m0["cut"]))

    warm_walls, cold_walls, drifts = [], [], []
    for i, batch in enumerate(batches):
        session.apply(batch)
        out = repartition(session, ctx, k=BENCH_K, epsilon=BENCH_EPS,
                          seed=1)
        warm_walls.append(
            out.warm_wall_s if out.warm_wall_s is not None
            else out.wall_s)
        # the cold twin: the per-step from-scratch run warm replaced
        cold_solver = KaMinPar(context_from_preset("default"))
        cold_solver.set_graph(session.graph)
        t0 = time.perf_counter()
        cold_part = cold_solver.compute_partition(
            k=BENCH_K, epsilon=BENCH_EPS, seed=1)
        cold_walls.append(time.perf_counter() - t0)
        cold_cut = int(cold_solver.result_metrics(
            session.graph, cold_part)["cut"])
        drifts.append(abs(out.cut - cold_cut) / max(cold_cut, 1))
    speedup = (sum(cold_walls) / len(cold_walls)) / max(
        sum(warm_walls) / len(warm_walls), 1e-9)
    return round(speedup, 2), round(max(drifts), 4)


def lint_keys(seconds=None) -> dict:
    """The BENCH line's static-analysis key (round 17, tpulint v2):
    wall seconds of a full-package `lint_paths` run with every rule
    (call graph + R9 schema pins included) — the analysis itself is a
    commit-gate stage, so its cost is a trend worth watching.  Always
    present, null when the lint run errored."""
    return {"tpulint_seconds": seconds}


def _measure_lint():
    """Wall seconds of one full-rule tpulint pass over the package."""
    import time

    from kaminpar_tpu.lint import LintConfig, lint_paths

    pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "kaminpar_tpu")
    t0 = time.perf_counter()
    findings = lint_paths([pkg], LintConfig())
    seconds = time.perf_counter() - t0
    assert findings == [], (
        f"bench lint pass found {len(findings)} finding(s); the package "
        "must stay clean")
    return round(seconds, 2)


def quality_keys(report) -> dict:
    """The BENCH line's quality-attribution keys from an embedded run
    report (telemetry/quality.py totals); every key present, null when
    the report carries no attribution."""
    totals = ((report or {}).get("quality") or {}).get("totals") or {}
    return {key: totals.get(key) for key in QUALITY_KEYS}


#: Execution-ledger keys (round 19, telemetry/ledger.py): whether the
#: headline hbm_util came from launch-joined measured figures (every
#: launch ran a cost-captured executable — `honest`), the total launch
#: count, and the per-phase host<->device transfer bytes.  Same
#: never-vanish contract (null = the report carries no ledger, ABSENCE
#: = silent coverage loss, gated by bench_trend from r06 on).
LEDGER_KEYS = ("util_honest", "launches_total", "transfer_bytes_per_phase")


def ledger_keys(report) -> dict:
    """The BENCH line's execution-ledger keys from an embedded run
    report; every key present, null when the report has no ledger."""
    rep = report or {}
    perf_totals = (rep.get("perf") or {}).get("totals") or {}
    ledger = rep.get("ledger") or {}
    by_phase = (ledger.get("transfers") or {}).get("by_phase") or None
    return {
        "util_honest": perf_totals.get("util_honest"),
        "launches_total": perf_totals.get("launches"),
        "transfer_bytes_per_phase": by_phase,
    }


def external_keys(seconds=None, overlap=None) -> dict:
    """The BENCH line's out-of-core streaming keys; every key present,
    null when the external measurement was skipped or failed."""
    return {"external_seconds": seconds, "stream_overlap": overlap}


def _measure_external():
    """One `--scheme external` partition of the medium bench graph under
    a forced budget at 25% of its in-core estimate: (wall seconds,
    overlap fraction from the run's `external` report section).  The
    scale half of the north star gets a trend line next to the in-core
    kernels."""
    import time

    import numpy as np

    from kaminpar_tpu import telemetry
    from kaminpar_tpu.context import PartitioningMode
    from kaminpar_tpu.graphs.factories import generate
    from kaminpar_tpu.kaminpar import KaMinPar, context_from_preset
    from kaminpar_tpu.resilience.memory import estimate_run_bytes

    graph = generate(f"rmat;n={MED_N};m={MED_M};seed={MED_SEED}")
    ctx = context_from_preset("default")
    ctx.partitioning.mode = PartitioningMode.EXTERNAL
    ctx.resilience.memory_budget = float(
        int(estimate_run_bytes(graph.n, graph.m, BENCH_K) * 0.25)
    )
    solver = KaMinPar(ctx)
    solver.set_graph(graph)
    # the external section rides on the telemetry stream; this
    # measurement runs AFTER the main loop disabled telemetry, so it
    # must arm its own stream or overlap would be permanently null —
    # the r05 silent-coverage-loss class, just for the new keys
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        t0 = time.perf_counter()
        part = solver.compute_partition(
            k=BENCH_K, epsilon=BENCH_EPS, seed=1
        )
        wall = time.perf_counter() - t0
        assert len(part) == graph.n and len(np.unique(part)) <= BENCH_K
        section = telemetry.run_info().get("external") or {}
        overlap = section.get("overlap_frac")
    finally:
        if not was_enabled:
            telemetry.disable()
        telemetry.reset()
    return round(wall, 2), overlap


MED_N = 1 << 16
MED_M = 600_000
MED_SEED = 3
BENCH_K = 16
BENCH_EPS = 0.03


def _init_platform() -> None:
    """Use the default (TPU/axon) backend; fall back to CPU when the chip
    is unreachable so the bench always reports a number."""
    import jax

    # persistent compile cache: the pipeline compiles one executable per
    # shape bucket; caching them on disk makes later runs start fast
    cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    try:
        jax.devices()
    except RuntimeError as e:
        import sys

        print(f"bench: default backend unavailable ({e}); CPU fallback",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        from jax.extend.backend import clear_backends

        clear_backends()


def _measure_large_coarsening(
    reps: int = 2, budget_s: float = 0.0
) -> float | None:
    """LP+coarsening wall-clock on the LARGE (10M-edge) bench graph —
    the scale where the repo's CPU-vs-TPU comparison is meaningful (the
    medium graph is launch-floor-dominated; see docs/performance.md).
    Same graph and phase boundary as BASELINE_CPU.json's
    large10m_coarsening_s (scripts/measure_cpu_baseline.py --large).
    Returns seconds (best of `reps` runs — the first pays
    executable-cache loads even when compiled; the CPU denominator is
    likewise the binary's fastest run), or None on failure (the bench
    line then reports the large-graph keys as null).

    `budget_s` > 0 bounds the measurement wall (the CPU fallback): a
    run that blows the budget mid-hierarchy reports None — a null
    metric, never a silently-partial number."""
    import time

    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.partitioning.coarsener import Coarsener
    from kaminpar_tpu.presets import create_context_by_preset_name

    host = make_rmat(1 << 20, 10_000_000, seed=7)
    ctx = create_context_by_preset_name("default")
    ctx.partition.setup(host, k=BENCH_K, epsilon=BENCH_EPS)
    ctx.seed = 1
    best = None
    for _ in range(max(reps, 1)):
        dgraph = device_graph_from_host(host)
        int(jnp.sum(dgraph.src[:1]))  # force the upload before timing
        coarsener = Coarsener(ctx, dgraph, host.n)
        threshold = max(2 * ctx.coarsening.contraction_limit, 2)
        t0 = time.perf_counter()
        while coarsener.current_n > threshold:
            if budget_s > 0 and time.perf_counter() - t0 > budget_s:
                import sys

                print(
                    f"bench: 10M coarsening blew its {budget_s:.0f}s "
                    "budget; reporting null",
                    file=sys.stderr,
                )
                return best
            if not coarsener.coarsen():
                break
        int(jnp.sum(coarsener.current.src[:1]))  # readback-synced stop
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _measure_large_total(reps: int = 2, time_budget: float = 0.0):
    """Full end-to-end partition of the 10M-edge bench graph (default
    preset, warm cache): total wall + cut.  Catches SCALE regressions the
    medium line cannot (VERDICT r3 weak #4); compares against the
    reference binary's cut on the same graph
    (BASELINE_CPU.json large10m_edge_cut).

    `time_budget` > 0 arms the PR-5 anytime deadline so the CPU
    fallback stays wall-bounded: the run winds down at a pipeline
    barrier and still returns a gate-valid partition (cut/feasible stay
    honest numbers; the wall reads as the budget ceiling)."""
    import time

    import numpy as np

    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.graphs.host import host_partition_metrics
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    host = make_rmat(1 << 20, 10_000_000, seed=7)
    # best of `reps`: the first run pays per-process executable-cache
    # loads even when fully compiled (solo warm steady state is the
    # honest figure; the CPU denominator is likewise the binary's
    # fastest run)
    total = None
    part = None
    for _ in range(max(reps, 1)):
        p = KaMinPar("default")
        if time_budget > 0:
            p.ctx.resilience.time_budget = float(time_budget)
        p.set_output_level(OutputLevel.QUIET)
        t0 = time.perf_counter()
        part = p.set_graph(host).compute_partition(
            k=BENCH_K, epsilon=BENCH_EPS, seed=1
        )
        dt = time.perf_counter() - t0
        total = dt if total is None else min(total, dt)
    res = host_partition_metrics(host, part, BENCH_K)
    nw = host.node_weight_array()
    cap = (1 + BENCH_EPS) * np.ceil(nw.sum() / BENCH_K)
    feasible = bool(res["block_weights"].max() <= cap)
    return round(total, 1), int(res["cut"]), feasible


def _measure_utilization():
    """Achieved-bandwidth probes for the primitive ops the pipeline is
    built from (VERDICT r3: prove or break the 'structural floor' with
    utilization data).  Useful bytes / wall vs the v5e HBM peak
    (~819 GB/s); the scalar gather lands around 0.1% — the per-index
    cost is XLA's lowering, not the memory system (full table:
    scripts/microbench_gather.py, docs/performance.md round-4 section)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    M, N = 1 << 24, 1 << 20
    rng = np.random.RandomState(0)
    dst = jnp.asarray(rng.randint(0, N, M).astype(np.int32))
    tab = jnp.asarray(rng.randint(0, 100, N).astype(np.int32))
    vals = jnp.asarray(rng.randint(0, 100, M).astype(np.int32))

    def probe(fn, useful_bytes, *args):
        f = jax.jit(fn)
        out = f(*args)
        int(jnp.sum(jax.tree_util.tree_leaves(out)[0].reshape(-1)[:1]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = f(*args)
            int(jnp.sum(jax.tree_util.tree_leaves(out)[0].reshape(-1)[:1]))
            best = min(best, time.perf_counter() - t0)
        return round(100.0 * useful_bytes / best / 1e9 / 819.0, 3)

    out = {
        "util_gather_pct_hbm": probe(
            lambda t, d: t[d], M * 12, tab, dst
        ),
        "util_scatter_add_pct_hbm": probe(
            lambda v, d: jnp.zeros(N, jnp.int32).at[d].add(v),
            M * 12 + N * 8, vals, dst,
        ),
        "util_stream_cumsum_pct_hbm": probe(
            jnp.cumsum, M * 8, vals
        ),
    }
    # the round-5 lane-routed gather at the same (M, N) shape — the
    # Pallas dynamic_gather answer to the XLA gather floor above
    try:
        from kaminpar_tpu.ops.lane_gather import (
            build_gather_plan,
            lane_gather,
            lane_gather_supported,
        )

        if lane_gather_supported():
            plan = build_gather_plan(dst, N)
            out["util_lane_gather_pct_hbm"] = probe(
                lambda t: lane_gather(t, plan), M * 12, tab
            )
    except Exception:
        pass
    return out


def _bench_line() -> dict:
    import numpy as np

    _init_platform()

    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    from kaminpar_tpu.graphs.host import host_partition_metrics

    host = make_rmat(MED_N, MED_M, seed=MED_SEED)
    nw = host.node_weight_array()
    cap = (1 + BENCH_EPS) * np.ceil(nw.sum() / BENCH_K)

    # best of two seeds — the same methodology the recorded reference
    # number uses (BASELINE_CPU.json medium_note: best of seeds 1-2);
    # a feasible candidate always beats an infeasible one
    import time

    from kaminpar_tpu.utils import timer

    # telemetry for the embedded run report: the BENCH line carries the
    # same schema as --report-json so the perf trajectory and ad-hoc
    # runs are directly comparable (telemetry/run_report.schema.json).
    # Spans must accrue DURING the run, so telemetry is on inside the
    # timed region; the facade's result-metrics pass that entails costs
    # ~14 ms on the medium graph (<1% of total_seconds — within seed
    # noise vs pre-telemetry BENCH lines).
    from kaminpar_tpu import telemetry

    telemetry.enable()

    # integrity-sentinel overhead accrues on the module's wall counter
    # (resilience/integrity.py): zero it here so the measured region is
    # exactly the timed seeds below, not any warmup run before them
    from kaminpar_tpu.resilience import integrity as integrity_mod

    integrity_mod.reset()

    best = None
    coarsening_times = []
    total_times = []
    lp_times = []
    contraction_times = []
    for seed in (1, 2):
        p = KaMinPar("default")
        p.set_output_level(OutputLevel.QUIET)
        t0 = time.perf_counter()
        cand = p.set_graph(host).compute_partition(
            k=BENCH_K, epsilon=BENCH_EPS, seed=seed
        )
        total_times.append(time.perf_counter() - t0)  # returns synced numpy
        # LP clustering + contraction wall-clock of this run, from the
        # hierarchical timer (compute_partition resets it; the coarsener
        # forces a scalar readback inside each lp scope, so attribution
        # is honest on the async remote backend).  The per-kernel split
        # (lp-clustering vs contraction) feeds the bench_trend kernel
        # columns — "which kernel regressed" is a read, not a dig.
        coarsening_times.append(
            timer.GLOBAL_TIMER.elapsed("partitioning", "coarsening")
        )
        lp_times.append(
            timer.GLOBAL_TIMER.elapsed(
                "partitioning", "coarsening", "lp-clustering"
            )
        )
        contraction_times.append(
            timer.GLOBAL_TIMER.elapsed(
                "partitioning", "coarsening", "contraction"
            )
        )
        cand_res = host_partition_metrics(host, cand, BENCH_K)
        cand_feasible = bool(cand_res["block_weights"].max() <= cap)
        # capture this run's report before the next compute resets the
        # telemetry stream; keep the one belonging to the best candidate
        try:
            from kaminpar_tpu.telemetry.report import build_run_report

            cand_report = build_run_report(extra_run={"bench_seed": seed})
        except Exception as e:  # never let telemetry break the line
            import sys

            print(f"bench: run-report build failed: {e}", file=sys.stderr)
            cand_report = None
        key = (not cand_feasible, cand_res["cut"])
        if best is None or key < best[0]:
            best = (key, cand_res, cand_feasible, cand_report)
    _, res, feasible, best_report = best
    telemetry.disable()
    cut = res["cut"]
    # times are min-over-seeds (steady state): the first seed's run may
    # include remote XLA compiles / cache loads, and the CPU denominator
    # is likewise the binary's fastest run
    coarsening_s = min(coarsening_times)
    total_s = min(total_times)
    # sentinel wall over BOTH timed seeds vs their total compute wall:
    # the < 3% dormancy budget as a measured figure, not a claim
    integrity_overhead = integrity_mod.overhead_pct(sum(total_times))

    vs = 0.0
    vs_cpu = None
    vs_cpu_10m = None
    coarsening_10m_s = None
    base = {}
    baseline_path = os.path.join(os.path.dirname(__file__), "BASELINE_CPU.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f)
        ref = base.get("medium_edge_cut")
        if feasible and ref:
            vs = ref / max(cut, 1)
        cpu_coarsening = base.get("medium_coarsening_s")
        if cpu_coarsening and coarsening_s > 0.01:
            # >1 means the TPU coarsening phase is FASTER than the
            # reference binary's (8-thread) coarsening on the same graph
            vs_cpu = round(cpu_coarsening / coarsening_s, 3)

    # large-graph speed ratio at >=10M edges — the scale that decides
    # the CPU-vs-TPU story.  BENCH_r05 silently dropped every 10M metric
    # because this section was gated on the accelerator being up; it now
    # runs on EVERY platform (the keys must never vanish from the
    # trajectory again) with CPU-sized effort: one rep instead of two
    # and the PR-5 anytime deadline bounding the end-to-end wall
    # (KAMINPAR_TPU_BENCH_LARGE_BUDGET_S, default 600 s on the CPU
    # fallback).  KAMINPAR_TPU_BENCH_SKIP_LARGE=1 still skips for quick
    # local runs.
    total_10m = cut_10m = feasible_10m = None
    util = {}
    import jax as _jax

    platform = _jax.devices()[0].platform
    on_accel = platform in ("tpu", "axon")
    if (
        base.get("large10m_coarsening_s")
        and os.environ.get("KAMINPAR_TPU_BENCH_SKIP_LARGE", "") != "1"
    ):
        reps = 2 if on_accel else 1
        # unset env -> platform default (0 = unbudgeted on the
        # accelerator, 600 s ceiling on the CPU fallback); an explicit
        # env value — including "0" — wins
        raw_budget = os.environ.get("KAMINPAR_TPU_BENCH_LARGE_BUDGET_S", "")
        budget = float(raw_budget) if raw_budget else (
            0.0 if on_accel else 600.0
        )
        try:
            coarsening_10m_s = _measure_large_coarsening(
                reps=reps, budget_s=budget
            )
        except Exception as e:  # never let the large run break the line
            import sys

            print(f"bench: large-graph measurement failed: {e}",
                  file=sys.stderr)
        if coarsening_10m_s and coarsening_10m_s > 0.01:
            vs_cpu_10m = round(
                base["large10m_coarsening_s"] / coarsening_10m_s, 3
            )
        try:
            total_10m, cut_10m, feasible_10m = _measure_large_total(
                reps=reps, time_budget=budget
            )
        except Exception as e:
            import sys

            print(f"bench: 10M end-to-end failed: {e}", file=sys.stderr)
    if os.environ.get("KAMINPAR_TPU_BENCH_SKIP_LARGE", "") != "1":
        # the kernel-utilization probes are seconds of work on any
        # platform — they ride every run (platform stamps the context:
        # on the CPU fallback they are smoke signals, not measurements)
        try:
            util = _measure_utilization()
        except Exception as e:
            import sys

            print(f"bench: utilization probe failed: {e}", file=sys.stderr)

    line = {
        "metric": "edge_cut_rmat600k_k16",
        "value": cut,
        "unit": "cut",
        "vs_baseline": round(vs, 3),
        "lp_coarsening_seconds": round(coarsening_s, 2),
        "total_seconds": round(total_s, 2),
        # per-kernel split of the coarsening wall (min over seeds, same
        # steady-state rule as coarsening_s) — the bench_trend kernel
        # regression gate reads these
        "kernel_seconds": {
            "lp": round(min(lp_times), 2),
            "contraction": round(min(contraction_times), 2),
        },
        # cuts are platform-independent; every WALL figure is only
        # meaningful on the TPU — "cpu" here means the tunnel was down
        # and the speed ratios must not be read as TPU numbers
        "platform": platform,
    }
    if vs_cpu is not None:
        line["vs_cpu_coarsening"] = vs_cpu
    # the 10M block is ALWAYS present (BENCH_r05 dropped it silently;
    # bench_trend --check now fails a round that loses these keys) —
    # null means the measurement errored, not that it was skipped
    line["lp_coarsening_10m_seconds"] = (
        round(coarsening_10m_s, 2) if coarsening_10m_s is not None else None
    )
    line["vs_cpu_coarsening_10m"] = vs_cpu_10m
    line["total_10m_seconds"] = total_10m
    line["cut_10m"] = cut_10m
    line["feasible_10m"] = feasible_10m
    ref_10m = base.get("large10m_edge_cut_k16")
    line["vs_baseline_cut_10m"] = (
        round(ref_10m / max(cut_10m, 1), 3)
        if (ref_10m and cut_10m and feasible_10m) else None
    )
    line.update(util)
    # the probe keys share the 10M block's always-present contract
    # (bench_trend gates on ABSENCE; null marks a skipped/failed probe)
    for key in ("util_gather_pct_hbm", "util_scatter_add_pct_hbm",
                "util_stream_cumsum_pct_hbm"):
        line.setdefault(key, None)
    # quality-attribution headline (telemetry/quality.py): which share
    # of the per-level cut gap is locked by coarsening vs left by
    # refinement — ALWAYS present (null = no attribution recorded), so
    # the trajectory can never silently lose the quality signal
    line.update(quality_keys(best_report))
    # out-of-core streaming coverage (round 13): a forced-budget
    # external run of the medium graph — always-present keys (null =
    # skipped/failed), so the scale path can never silently drop out
    # of the trajectory like the r05 10M block did
    ext_seconds = ext_overlap = None
    if os.environ.get("KAMINPAR_TPU_BENCH_SKIP_LARGE", "") != "1":
        try:
            ext_seconds, ext_overlap = _measure_external()
        except Exception as e:
            import sys

            print(f"bench: external measurement failed: {e}",
                  file=sys.stderr)
    line.update(external_keys(ext_seconds, ext_overlap))
    # supervised-serving latency (round 14): the containment boundary's
    # p95 — always-present key (null = skipped/failed), same r05-class
    # presence contract as the 10M/external blocks
    sup_p95 = sup_rps = sup_occ = None
    if os.environ.get("KAMINPAR_TPU_BENCH_SKIP_LARGE", "") != "1":
        try:
            sup_p95, sup_rps, sup_occ = _measure_supervised()
        except Exception as e:
            import sys

            print(f"bench: supervised measurement failed: {e}",
                  file=sys.stderr)
    line.update(supervised_key(sup_p95))
    # serving-throughput coverage (round 16, fleet observatory): the
    # same batch's rps + mean executable occupancy — always-present
    # keys (null = skipped/failed), same r05-class presence contract
    line.update(throughput_keys(sup_rps, sup_occ))
    # dynamic-repartitioning coverage (round 15): warm-vs-cold speedup
    # and cut drift over a short delta chain — always-present keys
    # (null = skipped/failed), same r05-class presence contract
    dyn_speedup = dyn_drift = None
    if os.environ.get("KAMINPAR_TPU_BENCH_SKIP_LARGE", "") != "1":
        try:
            dyn_speedup, dyn_drift = _measure_dynamic()
        except Exception as e:
            import sys

            print(f"bench: dynamic measurement failed: {e}",
                  file=sys.stderr)
    line.update(dynamic_keys(dyn_speedup, dyn_drift))
    # static-analysis coverage (round 17, tpulint v2): the commit gate's
    # own wall — always-present key (null = errored), same r05-class
    # presence contract; also re-asserts the zero-finding state from
    # inside the bench
    lint_s = None
    try:
        lint_s = _measure_lint()
    except Exception as e:
        import sys

        print(f"bench: lint measurement failed: {e}", file=sys.stderr)
    line.update(lint_keys(lint_s))
    # launch-honest utilization + transfer-bytes coverage (round 19,
    # execution ledger): whether the perf headline is launch-joined
    # truth or a compile-time lower bound, plus where the host<->device
    # bytes went — always-present keys, same r05-class presence contract
    line.update(ledger_keys(best_report))
    # integrity-sentinel overhead (round 20, resilience/integrity.py):
    # host-side sentinel wall as a percentage of the measured partition
    # wall — ALWAYS present (0.0 when the kill switch disabled the
    # layer), same r05-class presence contract, advisory column in
    # bench_trend
    line["integrity_overhead_pct"] = integrity_overhead
    if best_report is not None:
        # rating-engine choices of the best run (ops/rating.py
        # selection, from the embedded report's `rating` section):
        # per-engine level counts, e.g. {"scatter": 3, "dense": 4}
        line["rating_engines"] = (
            best_report.get("rating", {}).get("engines", {})
        )
        # perf-observatory headline figures promoted next to cut/seconds
        # (the full per-scope breakdown rides in the embedded report's
        # `perf` section; scripts/bench_trend.py renders these columns)
        perf_totals = best_report.get("perf", {}).get("totals", {})
        for src, dst in (("hbm_util", "hbm_util"),
                         ("pad_waste", "pad_waste")):
            if perf_totals.get(src) is not None:
                line[dst] = perf_totals[src]
        # drop only OPTIONAL sections; everything the schema requires
        # (including events) stays, so the embedded report validates
        # against run_report.schema.json exactly like a --report-json file
        line["report"] = {
            k: v for k, v in best_report.items()
            if k not in ("timers_aggregated", "heap")
        }
    return line


#: stderr lines carrying any of these markers are machine noise, not
#: measurement output: the BENCH_r05 recorded tail was ~2 KB of ONE
#: XLA:CPU AOT loader machine-feature banner (cpu_aot_loader.cc
#: "Target machine feature ... not supported"), which drowned every
#: informative bench diagnostic out of the harness's tail window.
STDERR_NOISE_MARKERS = ("cpu_aot_loader.cc",)

#: Recorded-tail budget: after noise stripping, only the LAST lines up
#: to this many bytes are re-emitted (the harness tails stderr, so the
#: newest diagnostics are the ones that must survive).
STDERR_TAIL_CAP = 2048


def _filter_stderr_tail(raw: bytes) -> bytes:
    """Strip known-noise lines from captured bench stderr and keep the
    last genuinely informative lines within STDERR_TAIL_CAP bytes.

    Whole-line filtering only — any line without a noise marker passes
    through verbatim, so real warnings are never rewritten."""
    kept = [
        ln for ln in raw.decode("utf-8", "replace").splitlines()
        if ln.strip() and not any(m in ln for m in STDERR_NOISE_MARKERS)
    ]
    tail: list = []
    size = 0
    for ln in reversed(kept):
        size += len(ln) + 1
        if size > STDERR_TAIL_CAP and tail:
            break
        tail.append(ln)
    text = "\n".join(reversed(tail))
    return (text + "\n").encode("utf-8") if text else b""


def main() -> None:
    """Print the BENCH JSON line as the SOLE stdout line.

    Harness parsing used to depend on "the last stdout line survives XLA
    AOT loader warnings"; now every byte the measurement emits — python
    prints AND C-level noise (XLA loaders, absl banners) — is routed to
    stderr at the file-descriptor level, and only the final JSON line is
    written to the real stdout.  The stderr stream itself is captured
    and re-emitted through _filter_stderr_tail, so the harness's
    recorded tail carries the bench's own diagnostics instead of the
    ~2 KB cpu_aot_loader.cc machine-feature banner (the BENCH_r05 tail
    regression)."""
    import sys
    import tempfile

    sys.stdout.flush()
    sys.stderr.flush()
    real_stdout = os.dup(1)
    real_stderr = os.dup(2)
    cap = tempfile.TemporaryFile()
    os.dup2(cap.fileno(), 2)  # capture stderr for noise filtering
    os.dup2(2, 1)  # fd-level: C/C++ writes to fd 1 land on stderr too
    try:
        line = _bench_line()
    finally:
        sys.stdout.flush()
        sys.stderr.flush()
        os.dup2(real_stdout, 1)
        os.dup2(real_stderr, 2)
        os.close(real_stdout)
        os.close(real_stderr)
        try:
            cap.seek(0)
            filtered = _filter_stderr_tail(cap.read())
            if filtered:
                sys.stderr.buffer.write(filtered)
                sys.stderr.buffer.flush()
        except Exception:
            pass  # tail filtering must never eat the BENCH line
        finally:
            cap.close()
    print(json.dumps(line))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
