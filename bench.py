#!/usr/bin/env python
"""Round benchmark: device LP clustering + contraction wall-clock.

Measures the framework's hot phases (SURVEY.md §3.3: LP iteration +
cluster contraction — HOT LOOP 1 and 2 of the reference's call stack) on a
10M-edge RMAT graph, the BASELINE.md workload class, over two multilevel
coarsening levels.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}
vs_baseline is the CPU reference speedup factor: cpu_seconds / our_seconds,
where cpu_seconds comes from BASELINE_CPU.json (measured once with the
reference KaMinPar binary's coarsening timer on the same graph; see
scripts/measure_cpu_baseline.py).  Target per BASELINE.md: >= 4x.
"""

from __future__ import annotations

import json
import os
import time

RMAT_N = 1 << 20
RMAT_M = 10_000_000
SEED = 42
BENCH_K = 16
BENCH_EPS = 0.03


def build_graph():
    from kaminpar_tpu.graphs.factories import make_rmat

    return make_rmat(RMAT_N, RMAT_M, seed=SEED)


def run_pipeline(host, graph, seed: int) -> int:
    """The product's full coarsening phase (Coarsener: LP clustering +
    contraction until the contraction limit), matching the reference's
    'coarsening' timer subtree.  Returns the coarsest n."""
    import jax

    from kaminpar_tpu.partitioning.coarsener import Coarsener
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("default")
    ctx.seed = seed
    ctx.partition.setup(host, k=BENCH_K, epsilon=BENCH_EPS)
    coarsener = Coarsener(ctx, graph, int(host.n))
    threshold = max(2 * ctx.coarsening.contraction_limit, 2)  # deep.py stop
    while coarsener.current_n > threshold:
        if not coarsener.coarsen():
            break
    jax.block_until_ready(coarsener.current.node_w)
    return coarsener.current_n


def _init_platform() -> str:
    """Use the default (TPU/axon) backend; fall back to CPU when the chip
    is unreachable so the bench always reports a number."""
    import jax

    try:
        return jax.devices()[0].platform
    except RuntimeError as e:
        import sys

        print(f"bench: default backend unavailable ({e}); CPU fallback",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        from jax.extend.backend import clear_backends

        clear_backends()
        return jax.devices()[0].platform


def main() -> None:
    import jax

    from kaminpar_tpu.graphs.csr import device_graph_from_host

    # persistent compile cache: the multilevel pipeline compiles one
    # executable per shape bucket (~10 buckets x several kernels); caching
    # them on disk turns the ~10-minute first-run warmup into seconds on
    # every later run
    cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    _init_platform()

    host = build_graph()
    graph = device_graph_from_host(host)
    jax.block_until_ready(graph.node_w)

    run_pipeline(host, graph, seed=0)  # warmup: compile every shape bucket

    best = float("inf")
    for rep in range(3):
        t0 = time.perf_counter()
        run_pipeline(host, graph, seed=rep)
        best = min(best, time.perf_counter() - t0)

    vs = 0.0
    baseline_path = os.path.join(os.path.dirname(__file__), "BASELINE_CPU.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            cpu = json.load(f)
        cpu_s = cpu.get("lp_coarsening_s")
        if cpu_s:
            vs = cpu_s / best

    print(
        json.dumps(
            {
                "metric": "lp_coarsening_wall_rmat10M",
                "value": round(best, 4),
                "unit": "s",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
