"""Builder-surface example (analog of examples/kaminpar/shm_toy_graph_example.cc).

Shows the copy_graph ingestion path (raw CSR arrays), custom per-block
maximum weights, and rerunning with different seeds.
"""

import numpy as np

from kaminpar_tpu import KaMinPar


def main() -> None:
    # triangle plus a pendant node: 0-1, 1-2, 2-0, 2-3
    xadj = np.array([0, 2, 4, 7, 8], dtype=np.int64)
    adjncy = np.array([1, 2, 0, 2, 0, 1, 3, 2], dtype=np.int32)
    vwgt = np.array([1, 1, 2, 1], dtype=np.int32)

    solver = KaMinPar("fast").copy_graph(xadj, adjncy, vwgt=vwgt)

    # explicit per-block weight caps instead of k/epsilon
    part = solver.compute_partition(
        max_block_weights=np.array([3, 3], dtype=np.int64), seed=1
    )
    print("custom caps ->", part.tolist())

    best = min(
        (solver.compute_partition(k=2, epsilon=0.1, seed=s) for s in range(3)),
        key=lambda p: (p[:3] != p[0]).sum(),
    )
    print("best of 3 seeds ->", best.tolist())


if __name__ == "__main__":
    main()
