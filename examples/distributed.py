"""Multi-chip example (analog of the dKaMinPar usage in examples/).

Partitions a generated RMAT graph over a device mesh.  On a CPU host,
expose virtual devices first:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed.py
"""

from kaminpar_tpu.graphs.factories import make_rmat
from kaminpar_tpu.graphs.host import host_partition_metrics
from kaminpar_tpu.parallel import dKaMinPar


def main() -> None:
    graph = make_rmat(1 << 12, 1 << 15, seed=7)

    solver = dKaMinPar("default")  # mesh over all visible devices
    part = solver.set_graph(graph).compute_partition(
        k=8, epsilon=0.03, seed=1
    )

    res = host_partition_metrics(graph, part, 8)
    print("edge cut:", res["cut"])
    print("imbalance:", round(res["imbalance"], 4))


if __name__ == "__main__":
    main()
