"""Minimal end-to-end example (analog of examples/simple/main.cc).

Builds a small toy graph in memory, partitions it into 2 blocks, and
prints the cut and block weights.
"""

import numpy as np

import kaminpar_tpu as ktp
from kaminpar_tpu.graphs.factories import make_grid_graph
from kaminpar_tpu.graphs.host import host_partition_metrics


def main() -> None:
    # 4x4 grid graph: 16 nodes, rook adjacency
    graph = make_grid_graph(4, 4)

    part = (
        ktp.KaMinPar("default")
        .set_graph(graph)
        .compute_partition(k=2, epsilon=0.03, seed=1)
    )

    res = host_partition_metrics(graph, part, 2)
    print("partition:", part.tolist())
    print("edge cut:", res["cut"])
    print("block weights:", res["block_weights"].tolist())
    assert res["imbalance"] <= 0.03 + 1e-9


if __name__ == "__main__":
    main()
