"""NetworKit binding example (analog of examples/bindings-networkit).

Requires the external `networkit` package; the adapter mirrors the
reference binding surface
(bindings/networkit: kaminpar.KaMinPar(G).computePartitionWithEpsilon).
"""


def main() -> None:
    try:
        import networkit as nk
    except ImportError:
        print("networkit not installed; skipping (the adapter is gated)")
        return

    from kaminpar_tpu.bindings.networkit import NetworKitKaMinPar

    import numpy as np

    G = nk.generators.HyperbolicGenerator(1000, k=8).generate()
    partition = NetworKitKaMinPar(G).computePartitionWithEpsilon(4, 0.03)
    print("block sizes:", np.bincount(partition, minlength=4).tolist())


if __name__ == "__main__":
    main()
