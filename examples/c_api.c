/* C-ABI example (analog of examples/bindings-c).
 *
 * Build the shared library first:
 *   python -m kaminpar_tpu.native.build_capi
 * then:
 *   gcc examples/c_api.c -I include -L kaminpar_tpu/native \
 *       -lckaminpar_tpu -o /tmp/c_api_example
 *   LD_LIBRARY_PATH=kaminpar_tpu/native PYTHONPATH=$PWD /tmp/c_api_example
 *
 * (PYTHONPATH is only needed when the package is not installed; the
 * shared library embeds a Python interpreter that imports kaminpar_tpu.)
 */
#include <stdint.h>
#include <stdio.h>

#include "ckaminpar_tpu.h"

int main(void) {
  /* triangle plus pendant node (METIS convention: both edge directions) */
  int64_t xadj[] = {0, 2, 4, 7, 8};
  int32_t adjncy[] = {1, 2, 0, 2, 0, 1, 3, 2};
  int32_t out[4];

  kmp_partitioner *p = kmp_create("fast", /*seed=*/1);
  if (!p) {
    fprintf(stderr, "failed to create partitioner\n");
    return 1;
  }

  int64_t cut = kmp_compute_partition(p, 4, xadj, adjncy, NULL, NULL,
                                      /*k=*/2, /*epsilon=*/0.1, out);
  if (cut < 0) {
    fprintf(stderr, "error: %s\n", kmp_last_error(p));
    kmp_free(p);
    return 1;
  }

  printf("cut=%lld partition=[%d %d %d %d]\n", (long long)cut, out[0], out[1],
         out[2], out[3]);
  kmp_free(p);
  return 0;
}
